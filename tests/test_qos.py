"""Serving-QoS fault paths (pilosa_tpu/qos): admission shedding,
deadline propagation, hedged replica reads, circuit breaking.

Fault injection follows the repo idiom (test_serving_pipeline,
test_cluster): in-process servers with monkeypatched seams — a stalled
replica is that node's ``API.query_raw`` sleeping, a burst is real
concurrent HTTP clients against a blocked executor. The acceptance
shapes from ISSUE 1: a 5 s-stall replica at replica_n=2 answers a
500 ms-deadline query via hedge in < 500 ms; a burst beyond the
admission limit yields 429s (not queue growth); shed/hedge/deadline
series are visible in GET /metrics.
"""

import threading
import time
import urllib.error
import urllib.request

import pytest

from cluster_helpers import make_cluster, req, seed, uri
from pilosa_tpu.qos import (
    AdmissionController,
    AdmissionError,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    HedgePolicy,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _close_all(servers):
    for s in servers:
        s.close()


def _stall(server, seconds):
    """Make one node's query handling (local AND remote sub-queries)
    sleep: the slow-replica fault."""
    orig = server.api.query_raw

    def stalled(*args, **kwargs):
        time.sleep(seconds)
        return orig(*args, **kwargs)

    server.api.query_raw = stalled
    return orig


def _remote_shard(servers, index="i"):
    """A (shard, primary, replicas) triple whose owners exclude node 0,
    so a query from node 0 must take the remote fan-out."""
    cluster = servers[0].api.cluster
    for shard in range(64):
        owners = cluster.shard_nodes(index, shard)
        if all(n.id != cluster.local.id for n in owners):
            return shard, owners
    raise AssertionError("no shard routed fully remote from node 0")


# ---------------------------------------------------------------- unit: QoS


class TestDeadline:
    def test_after_and_expiry(self):
        d = Deadline.after(0.05)
        assert not d.expired
        assert 0 < d.remaining() <= 0.05
        d.check()  # not expired: no raise
        time.sleep(0.06)
        assert d.expired
        with pytest.raises(DeadlineExceeded):
            d.check("unit")

    def test_wire_budget_roundtrip(self):
        d = Deadline.after(0.5)
        ms = d.to_millis()
        assert 0 < ms <= 500
        d2 = Deadline.from_millis(ms)
        # re-anchored budget is within a scheduling hiccup of the original
        assert abs(d2.remaining() - d.remaining()) < 0.1

    def test_to_millis_floor(self):
        # an expired deadline still serializes to >= 1ms: expiry is
        # raised locally by check(), never encoded as a 0 budget
        assert Deadline.after(-1).to_millis() == 1


class TestAdmission:
    def test_global_limit_sheds_and_releases(self):
        gate = AdmissionController(max_inflight=2, retry_after=3.0)
        s1 = gate.admit("a")
        s2 = gate.admit("b")
        with pytest.raises(AdmissionError) as ei:
            gate.admit("c")
        assert ei.value.retry_after == 3.0
        assert gate.metrics() == {"admitted_total": 2, "shed_total": 1,
                                  "inflight": 2}
        s1.release()
        s1.release()  # idempotent: double release must not free 2 tokens
        gate.admit("c").release()
        s2.release()
        assert gate.inflight == 0

    def test_tenant_quota_isolates_hot_tenant(self):
        gate = AdmissionController(max_inflight=4, tenant_max=2)
        gate.admit("hot")
        gate.admit("hot")
        with pytest.raises(AdmissionError):  # hot tenant at its quota
            gate.admit("hot")
        # other tenants still admitted: the node has global headroom
        gate.admit("cold")
        gate.admit("cold2")

    def test_unlimited_gate_tracks_inflight(self):
        gate = AdmissionController()  # 0 = off
        slots = [gate.admit("t") for _ in range(100)]
        assert gate.inflight == 100
        for s in slots:
            s.release()
        assert gate.inflight == 0 and gate.shed == 0


class TestHedgePolicy:
    def test_delay_tracks_p95_after_warmup(self):
        pol = HedgePolicy(initial_delay=0.25)
        assert pol.delay() == 0.25  # cold: configured initial delay
        for _ in range(19):
            pol.record(0.010)
        assert pol.delay() == 0.25  # still under MIN_SAMPLES
        pol.record(0.010)
        assert abs(pol.delay() - 0.010) < 1e-9  # warmed: p95 of samples

    def test_budget_enforced_as_fraction_of_primaries(self):
        pol = HedgePolicy(budget_fraction=0.05)
        pol.note_primary()
        assert pol.try_hedge()  # the +1 seat: first slow read may hedge
        assert not pol.try_hedge()  # budget gone at 1 primary
        for _ in range(20):  # 21 primaries: 0.05*21+1 ≈ 2 hedge seats
            pol.note_primary()
        assert pol.try_hedge()
        assert not pol.try_hedge()
        m = pol.metrics()
        assert m["hedges_total"] == 2
        assert m["hedge_budget_denied_total"] == 2

    def test_zero_budget_never_hedges(self):
        pol = HedgePolicy(budget_fraction=0.0)
        for _ in range(100):
            pol.note_primary()
        assert not pol.try_hedge()


class TestCircuitBreaker:
    def test_open_half_open_close(self):
        br = CircuitBreaker(threshold=3, cooldown=0.05)
        for _ in range(2):
            br.record_failure()
        assert br.allow()  # under threshold: still closed
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()  # open: fail fast
        time.sleep(0.06)
        assert br.allow()  # cooldown passed: the half-open probe
        assert not br.allow()  # exactly ONE probe, not a thundering herd
        br.record_success()
        assert br.state == "closed" and br.allow()

    def test_half_open_failure_reopens(self):
        br = CircuitBreaker(threshold=1, cooldown=0.05)
        br.record_failure()
        assert br.state == "open"
        time.sleep(0.06)
        assert br.allow()  # probe
        br.record_failure()  # probe failed
        assert br.state == "open" and not br.allow()
        assert br.opened_total == 2

    def test_stale_success_does_not_close_open_breaker(self):
        """A success from a read sent BEFORE the node flapped must not
        cancel the cooldown: only the half-open probe may close an open
        breaker, or traffic resumes to a still-sick node."""
        br = CircuitBreaker(threshold=1, cooldown=60)
        br.record_failure()
        assert br.state == "open"
        br.record_success()  # pre-flap in-flight read finally landed
        assert br.state == "open" and not br.allow()

    def test_inconclusive_probe_releases_seat(self):
        """A probe whose request dies without a node verdict (deadline
        expiry, deterministic 4xx) must release the half-open seat —
        otherwise allow() returns False forever and the node is locked
        out until restart."""
        br = CircuitBreaker(threshold=1, cooldown=0.05)
        br.record_failure()
        time.sleep(0.06)
        assert br.allow()  # the half-open probe
        br.record_inconclusive()  # e.g. the REQUEST's deadline expired
        assert br.state == "half-open"
        assert br.allow()  # seat released: the next request may probe
        br.record_success()
        assert br.state == "closed"


class TestBreakerClassification:
    def _exec(self):
        from pilosa_tpu.parallel.cluster_exec import ClusterExecutor
        from pilosa_tpu.qos import ServingQos

        ex = object.__new__(ClusterExecutor)  # classification needs only qos
        ex.qos = ServingQos()
        return ex

    def test_deadline_expiry_is_not_a_node_fault(self):
        """A transport timeout caused by the REQUEST's own capped TIGHT
        budget must not count against the node (deadline.py invariant):
        tight-deadline traffic would otherwise open a healthy node's
        breaker and fail generous-deadline queries behind it."""
        from pilosa_tpu.parallel.client import ClientError

        ex = self._exec()
        br = CircuitBreaker(threshold=1)
        expired = Deadline.after(-1)
        ex._record_breaker_outcome(
            br, ClientError("read timed out"), expired, elapsed=0.05)
        assert br.state == "closed"
        # a 4xx is deterministic — every replica would repeat it
        ex._record_breaker_outcome(
            br, ClientError("bad query", status=400), Deadline.after(10),
            elapsed=0.05)
        assert br.state == "closed"
        # the same transport fault with a LIVE budget is real evidence
        ex._record_breaker_outcome(
            br, ClientError("read timed out"), Deadline.after(10),
            elapsed=0.05)
        assert br.state == "open"

    def test_stalled_node_trips_breaker_even_at_expiry(self):
        """The converse guard: transport timeouts are budget-capped, so
        a truly stalled node always faults exactly at expiry — after it
        was given a fair chance (≥ 1 s and several× the hedge delay),
        the fault must count or its breaker would never open."""
        from pilosa_tpu.parallel.client import ClientError

        ex = self._exec()
        br = CircuitBreaker(threshold=1)
        ex._record_breaker_outcome(
            br, ClientError("read timed out"), Deadline.after(-0.001),
            elapsed=2.0)
        assert br.state == "open"


# --------------------------------------------------- integration: admission


class TestAdmissionOverHTTP:
    def test_burst_beyond_limit_yields_429_with_retry_after(self, tmp_path):
        """Acceptance: a burst beyond the admission limit sheds with 429
        + Retry-After while admitted requests complete — the queue does
        not grow. The executor is gated on an Event so 'in flight' is
        deterministic, not a race against service time."""
        from pilosa_tpu.server import Server, ServerConfig

        server = Server(ServerConfig(
            data_dir=str(tmp_path / "n0"), port=0, name="n0",
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
            qos_max_inflight=2,
        )).open()
        try:
            base = uri(server)
            req("POST", f"{base}/index/i", {})
            req("POST", f"{base}/index/i/field/f", {})
            gate = threading.Event()
            entered = threading.Semaphore(0)
            real_exec = server.api.executor.execute

            def blocked_execute(*a, **k):
                entered.release()
                assert gate.wait(30)
                return real_exec(*a, **k)

            server.api.executor.execute = blocked_execute
            results: list = []

            def client():
                try:
                    # writes take the eager path (request thread blocks
                    # inside the gated executor = admitted and in flight)
                    results.append(
                        ("ok", req("POST", f"{base}/index/i/query",
                                   b"Set(1, f=1)"))
                    )
                except urllib.error.HTTPError as e:
                    results.append(
                        ("http", e.code, e.headers.get("Retry-After"))
                    )

            first = [threading.Thread(target=client) for _ in range(2)]
            for t in first:
                t.start()
            # both tokens taken (clients are INSIDE the executor) before
            # the burst fires, so every burst request must shed
            assert entered.acquire(timeout=10)
            assert entered.acquire(timeout=10)
            burst = [threading.Thread(target=client) for _ in range(6)]
            for t in burst:
                t.start()
            for t in burst:
                t.join(timeout=30)
            shed = [r for r in results if r[0] == "http"]
            assert len(shed) == 6, results
            assert all(code == 429 for _, code, _ in shed)
            assert all(ra is not None and int(ra) >= 1 for *_, ra in shed)
            gate.set()
            for t in first:
                t.join(timeout=30)
            assert sum(1 for r in results if r[0] == "ok") == 2
            # shed/admit decisions are exported on /metrics
            text = req("GET", f"{base}/metrics", raw=True).decode()
            assert "pilosa_tpu_qos_shed_total 6" in text
            assert "pilosa_tpu_qos_admitted_total 2" in text
        finally:
            gate.set()
            server.close()

    def test_tenant_header_drives_quota(self, tmp_path):
        """Per-tenant quotas key off X-Pilosa-Tenant: one tenant at its
        quota sheds while another sails through the same node."""
        from pilosa_tpu.server import Server, ServerConfig

        server = Server(ServerConfig(
            data_dir=str(tmp_path / "n0"), port=0, name="n0",
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
            qos_max_inflight=8, qos_tenant_inflight=1,
        )).open()
        try:
            base = uri(server)
            req("POST", f"{base}/index/i", {})
            req("POST", f"{base}/index/i/field/f", {})
            gate = threading.Event()
            entered = threading.Semaphore(0)
            real_exec = server.api.executor.execute

            def blocked_execute(*a, **k):
                entered.release()
                assert gate.wait(30)
                return real_exec(*a, **k)

            server.api.executor.execute = blocked_execute

            def query(tenant):
                r = urllib.request.Request(
                    f"{base}/index/i/query", data=b"Set(1, f=1)",
                    method="POST", headers={"X-Pilosa-Tenant": tenant},
                )
                with urllib.request.urlopen(r, timeout=30) as resp:
                    return resp.status

            codes = {}
            t = threading.Thread(
                target=lambda: codes.__setitem__("first", query("alpha"))
            )
            t.start()
            assert entered.acquire(timeout=10)  # alpha is at quota 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                query("alpha")
            assert ei.value.code == 429
            t2 = threading.Thread(
                target=lambda: codes.__setitem__("beta", query("beta"))
            )
            t2.start()
            assert entered.acquire(timeout=10)  # beta admitted regardless
            gate.set()
            t.join(timeout=30)
            t2.join(timeout=30)
            assert codes == {"first": 200, "beta": 200}
        finally:
            gate.set()
            server.close()


# ---------------------------------------------- integration: deadline/hedge


class TestDeadlineAndHedging:
    def test_stalled_replica_hedged_within_deadline(self, tmp_path):
        """THE acceptance shape: replica_n=2, the primary owner of a
        remote shard stalls 5 s, and a 500 ms-deadline query still
        answers correctly in < 500 ms because the hedge fires at the
        (lowered) hedge delay and the sibling replica wins the race."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            shard, owners = _remote_shard(servers)
            cols = [shard * SHARD_WIDTH + c for c in (1, 2, 3)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            # warm the exact query first (device-program compile, plan
            # caches, wire negotiation): the timed window below must
            # measure the HEDGE, not a cold first-compile. Hedging is
            # held off during warm-up — a slow cold compile must not
            # hedge and spend the single bootstrap budget seat
            # (0.05 * primaries + 1) the timed rescue below needs
            servers[0].api.qos.hedge.initial_delay = 30.0
            warm = req("POST", f"{uri(servers[0])}/index/i/query",
                       b"Count(Row(f=1))")
            assert warm["results"][0] == 3
            # the PRIMARY (first live owner = where node 0 routes) stalls
            by_id = {s.api.cluster.local.id: s for s in servers}
            _stall(by_id[owners[0].id], 5.0)
            # hedge fast (cold-start delay, no p95 history yet)
            servers[0].api.qos.hedge.initial_delay = 0.03

            r = urllib.request.Request(
                f"{uri(servers[0])}/index/i/query",
                data=b"Count(Row(f=1))", method="POST",
                headers={"X-Pilosa-Deadline-Ms": "500"},
            )
            t0 = time.monotonic()
            with urllib.request.urlopen(r, timeout=30) as resp:
                import json

                out = json.loads(resp.read())
            elapsed = time.monotonic() - t0
            assert out["results"][0] == 3
            assert elapsed < 0.5, f"hedge too slow: {elapsed:.3f}s"
            m = servers[0].api.qos.metrics()
            assert m["hedges_total"] >= 1
            assert m["hedge_wins_total"] >= 1
            # and the counters are scrapeable
            text = req("GET", f"{uri(servers[0])}/metrics",
                       raw=True).decode()
            assert "pilosa_tpu_qos_hedges_total" in text
            assert "pilosa_tpu_qos_deadline_expired_total" in text
        finally:
            _close_all(servers)

    def test_deadline_bounds_dead_sole_replica(self, tmp_path):
        """replica_n=1 with the sole owner stalled: no replica can save
        the read, so the deadline must bound it — 504 in ~budget, not
        the 30 s client timeout."""
        servers = make_cluster(tmp_path, 2, replica_n=1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            shard, owners = _remote_shard(servers)
            cols = [shard * SHARD_WIDTH + 5]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1], "columns": cols})
            by_id = {s.api.cluster.local.id: s for s in servers}
            _stall(by_id[owners[0].id], 10.0)

            r = urllib.request.Request(
                f"{uri(servers[0])}/index/i/query",
                data=b"Count(Row(f=1))", method="POST",
                headers={"X-Pilosa-Deadline-Ms": "400"},
            )
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=30)
            elapsed = time.monotonic() - t0
            assert ei.value.code == 504, ei.value.code
            assert elapsed < 5.0, f"deadline not bounded: {elapsed:.3f}s"
            assert servers[0].api.qos.metrics()["deadline_expired_total"] >= 1
        finally:
            _close_all(servers)

    def test_deadline_budget_propagates_to_remote_hop(self, tmp_path):
        """The remote sub-query re-anchors the root's REMAINING budget:
        the replica sees a Deadline, and its remaining time never
        exceeds what the root had left."""
        servers = make_cluster(tmp_path, 2, replica_n=1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            shard, owners = _remote_shard(servers)
            cols = [shard * SHARD_WIDTH + 9]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1], "columns": cols})
            by_id = {s.api.cluster.local.id: s for s in servers}
            remote_srv = by_id[owners[0].id]
            seen = {}
            orig = remote_srv.api.query_raw

            def capture(*args, **kwargs):
                if kwargs.get("remote"):
                    seen["deadline"] = kwargs.get("deadline")
                return orig(*args, **kwargs)

            remote_srv.api.query_raw = capture
            r = urllib.request.Request(
                f"{uri(servers[0])}/index/i/query",
                data=b"Count(Row(f=1))", method="POST",
                headers={"X-Pilosa-Deadline-Ms": "60000"},
            )
            with urllib.request.urlopen(r, timeout=30) as resp:
                assert resp.status == 200
            assert seen.get("deadline") is not None
            assert 0 < seen["deadline"].remaining() <= 60.0
        finally:
            _close_all(servers)

    def test_server_default_deadline_only_on_edge_requests(self, tmp_path):
        """qos-default-deadline applies to EDGE queries only: a remote
        sub-query's budget belongs to its root — a locally-minted default
        would let one peer's tighter config fail healthy nodes."""
        from pilosa_tpu.server import Server, ServerConfig

        server = Server(ServerConfig(
            data_dir=str(tmp_path / "n0"), port=0, name="n0",
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
            qos_default_deadline=2.0,
        )).open()
        try:
            base = uri(server)
            req("POST", f"{base}/index/i", {})
            req("POST", f"{base}/index/i/field/f", {})
            req("POST", f"{base}/index/i/query", b"Set(1, f=1)")
            seen = {}
            orig = server.api.query_raw

            def capture(*args, **kwargs):
                key = "remote" if kwargs.get("remote") else "edge"
                seen[key] = kwargs.get("deadline")
                return orig(*args, **kwargs)

            server.api.query_raw = capture
            req("POST", f"{base}/index/i/query?remote=true&shards=0",
                b"Count(Row(f=1))")
            assert seen["remote"] is None
            req("POST", f"{base}/index/i/query", b"Count(Row(f=1))")
            assert seen["edge"] is not None
            assert 0 < seen["edge"].remaining() <= 2.0
        finally:
            server.close()

    def test_expired_deadline_rejected_before_dispatch(self, tmp_path):
        """A request whose budget is already gone when it reaches the
        executor is 504d without occupying a dispatch slot; an invalid
        header is a clean 400."""
        servers = make_cluster(tmp_path, 1, replica_n=1)
        try:
            base = uri(servers[0])
            req("POST", f"{base}/index/i", {})
            req("POST", f"{base}/index/i/field/f", {})
            req("POST", f"{base}/index/i/query", b"Set(1, f=1)")
            # stall ADMISSION-side: deadline expires between edge and
            # executor (simulated by an absurdly small budget + a slow
            # pre-execute hook)
            real_exec = servers[0].api.executor
            orig_submit = real_exec.submit

            def slow_submit(*a, **k):
                time.sleep(0.05)
                return orig_submit(*a, **k)

            real_exec.submit = slow_submit
            r = urllib.request.Request(
                f"{base}/index/i/query", data=b"Count(Row(f=1))",
                method="POST", headers={"X-Pilosa-Deadline-Ms": "1"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=30)
            assert ei.value.code == 504
            r = urllib.request.Request(
                f"{base}/index/i/query", data=b"Count(Row(f=1))",
                method="POST", headers={"X-Pilosa-Deadline-Ms": "nope"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=30)
            assert ei.value.code == 400
        finally:
            _close_all(servers)

    def test_hedge_budget_caps_extra_load(self, tmp_path):
        """With hedging disabled (budget fraction 0 takes the inline
        no-race fast path), a slow primary is NOT hedged: the read
        completes via the primary at its own pace — budget enforcement
        caps the extra load by degrading to reference behavior, never by
        failing reads."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            shard, owners = _remote_shard(servers)
            cols = [shard * SHARD_WIDTH + c for c in (1, 2)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            by_id = {s.api.cluster.local.id: s for s in servers}
            _stall(by_id[owners[0].id], 0.5)
            qos = servers[0].api.qos
            qos.hedge.budget_fraction = 0.0  # budget exhausted
            qos.hedge.initial_delay = 0.03

            t0 = time.monotonic()
            out = req("POST", f"{uri(servers[0])}/index/i/query",
                      b"Count(Row(f=1))")
            elapsed = time.monotonic() - t0
            assert out["results"][0] == 2
            # no hedge fired: the answer had to wait out the stall
            assert elapsed >= 0.4, elapsed
            assert qos.metrics()["hedges_total"] == 0
        finally:
            _close_all(servers)


# ------------------------------------------- integration: circuit breaking


class TestCircuitBreakerIntegration:
    def test_breaker_opens_on_dead_node_and_recovers(self, tmp_path):
        """Repeated transport faults to one node open its breaker —
        subsequent reads skip the dead node's transport timeout and go
        straight to the sibling replica — and the half-open probe closes
        it again once the node heals."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            shard, owners = _remote_shard(servers)
            cols = [shard * SHARD_WIDTH + 7]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1], "columns": cols})
            qos = servers[0].api.qos
            qos.hedge.budget_fraction = 0.0  # isolate the breaker path
            cluster = servers[0].api.cluster
            client = cluster.client
            dead_id = owners[0].id
            dead_uri = owners[0].uri
            real = type(client).query_node
            refused = {"n": 0}

            def flaky(self_, uri_, *a, **k):
                from pilosa_tpu.parallel.client import ClientError

                if uri_ == dead_uri and refused["n"] < 100:
                    refused["n"] += 1
                    raise ClientError(f"connect refused {uri_}")
                return real(self_, uri_, *a, **k)

            client.query_node = flaky.__get__(client)

            breaker = qos.breaker(dead_id)
            breaker.threshold = 2
            breaker.cooldown = 0.1

            def count():
                out = req("POST", f"{uri(servers[0])}/index/i/query",
                          b"Count(Row(f=1))")
                return out["results"][0]

            # each failed read records a breaker failure and survives
            # via replica fallback; node is re-marked NORMAL between
            # queries (heartbeat's job) so routing retries the primary
            for _ in range(2):
                assert count() == 1
                cluster.nodes[dead_id].state = "NORMAL"
            assert breaker.state == "open"
            faults_so_far = refused["n"]
            # circuit open: the next read never touches the dead node —
            # and the synthetic circuit-open error must not override the
            # heartbeat's NORMAL view (no contact was made)
            assert count() == 1
            assert refused["n"] == faults_so_far
            assert cluster.nodes[dead_id].state == "NORMAL"
            assert servers[0].api.qos.metrics()["breaker_open"] >= 1
            # heal the node and wait out the cooldown: the half-open
            # probe closes the breaker
            refused["n"] = 1000  # flaky() now passes through
            cluster.nodes[dead_id].state = "NORMAL"
            time.sleep(0.12)
            assert count() == 1
            assert breaker.state == "closed"
        finally:
            _close_all(servers)


# ------------------------------------------------------- pipeline satellite


class TestGatherLatch:
    def test_single_fast_client_does_not_latch_window(self):
        """ADVICE r5: a lone closed-loop client with sub-window service
        time keeps _recent_gap under the pressure threshold forever; the
        latch breaker must keep it on the zero-wait path (its waves are
        size 1, so the window buys nothing)."""
        from pilosa_tpu.server.pipeline import QueryPipeline

        pipe = QueryPipeline(api=None)
        pipe.GATHER_WINDOW_S = 0.2  # would be very visible if latched
        pipe._recent_gap = 0.001  # looks like pressure
        pipe._last_wave_size = 1  # ...but the last wave was a loner
        pipe._q.put(0)
        wave = [pipe._q.get()]
        t0 = time.monotonic()
        pipe._gather(wave)
        assert time.monotonic() - t0 < 0.05  # no 200 ms window paid
        assert len(wave) == 1

    def test_burst_reopens_window_within_one_wave(self):
        """The latch breaker must not lock OUT a real burst: a wave that
        greedy-drains >1 requests re-opens the window immediately."""
        from pilosa_tpu.server.pipeline import QueryPipeline

        pipe = QueryPipeline(api=None)
        pipe.GATHER_WINDOW_S = 0.2
        pipe._recent_gap = 0.001
        pipe._last_wave_size = 1  # closed by a quiet period
        for i in range(3):  # burst backlog
            pipe._q.put(i)

        def feeder():
            time.sleep(0.02)
            pipe._q.put(99)

        t = threading.Thread(target=feeder)
        t.start()
        pipe._q.put(-1)
        wave = [pipe._q.get()]
        pipe._gather(wave)
        t.join()
        # 1 + 3 drained + the straggler caught inside the window
        assert len(wave) == 5, wave
        assert pipe._last_wave_size == 5


# ------------------------------------------------------- cluster satellite


class TestCleanupRingSnapshot:
    def test_cleanup_ownership_frozen_against_midloop_join(self, tmp_path):
        """ADVICE r5 TOCTOU: a node-join landing while cleanup_unowned
        walks fragments must not swing ownership to the NEW ring — with
        one node and replica_n=1 every fragment is owned locally, and a
        join injected mid-walk must not delete any of them."""
        from pilosa_tpu.parallel.cluster import Cluster, Node
        from pilosa_tpu.storage import FieldOptions, Holder

        holder = Holder(str(tmp_path / "h"))
        holder.open()
        try:
            idx = holder.create_index("i")
            fld = idx.create_field("f", FieldOptions())
            for shard in range(8):
                fld.view("standard", create=True).fragment(
                    shard, create=True
                )
            cluster = Cluster(Node("a", "http://localhost:1"),
                              replica_n=1, holder=holder)
            real_partition = cluster.partition
            injected = {"done": False}

            def racing_partition(index, shard):
                if not injected["done"]:
                    injected["done"] = True
                    # the join lands mid-walk (as a concurrent
                    # node-join message would)
                    cluster.nodes["b"] = Node("b", "http://localhost:2")
                return real_partition(index, shard)

            cluster.partition = racing_partition
            removed = cluster.cleanup_unowned(members=["a"])
            assert removed == 0
            assert sorted(fld.view("standard").fragments) == list(range(8))
            # sanity: the LIVE ring does assign some shards to b now, so
            # the old code would have deleted sole copies here
            cluster.partition = real_partition
            live_owned = [
                s for s in range(8)
                if any(n.id == "a"
                       for n in cluster.shard_nodes("i", s))
            ]
            assert len(live_owned) < 8
        finally:
            holder.close()


# ----------------------------------------------------------- slow stress


@pytest.mark.slow
class TestQosStress:
    def test_sustained_burst_sheds_without_queue_growth(self, tmp_path):
        """Sustained overload (real service-time sleeps): shed count
        grows, in-flight stays bounded at the limit, and the node keeps
        answering /metrics throughout."""
        from pilosa_tpu.server import Server, ServerConfig

        server = Server(ServerConfig(
            data_dir=str(tmp_path / "n0"), port=0, name="n0",
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
            qos_max_inflight=4,
        )).open()
        try:
            base = uri(server)
            req("POST", f"{base}/index/i", {})
            req("POST", f"{base}/index/i/field/f", {})
            real_exec = server.api.executor.execute

            def slow_execute(*a, **k):
                time.sleep(0.2)
                return real_exec(*a, **k)

            server.api.executor.execute = slow_execute
            codes: list = []
            lock = threading.Lock()

            def client():
                for _ in range(4):
                    try:
                        req("POST", f"{base}/index/i/query", b"Set(1, f=1)")
                        code = 200
                    except urllib.error.HTTPError as e:
                        code = e.code
                    with lock:
                        codes.append(code)

            threads = [threading.Thread(target=client) for _ in range(16)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 30
            while any(t.is_alive() for t in threads):
                assert server.api.qos.admission.inflight <= 4
                req("GET", f"{base}/metrics", raw=True)  # stays live
                if time.monotonic() > deadline:
                    raise AssertionError("stress burst wedged")
                time.sleep(0.05)
            for t in threads:
                t.join()
            assert codes.count(200) >= 4  # admitted work completed
            assert codes.count(429) >= 1  # and the excess was shed
            assert server.api.qos.admission.inflight == 0
        finally:
            server.close()
