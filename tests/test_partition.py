"""Network-partition scenarios for the control plane.

Drives REAL in-process clusters through injected partitions
(testing/faults.py rules on the internal wire) and asserts the
partition-safety contract (docs/OPERATIONS.md failure model):

- quorum gating: a minority side degrades to serving locally-owned
  reads (writes shed 503) instead of declaring deaths, resizing, or
  deleting fragments by a minority view of ownership;
- corroborated death: suspect→dead needs ≥2 observers (all-but-self in
  2-node clusters) — a single cut link cannot amputate a live node;
- epoch fencing: a partitioned ex-coordinator healing back cannot
  un-gate queries, re-trigger resizes, or delete fragments with
  commands minted before the partition;
- rejoin: an evicted node that heals detects its eviction and rejoins
  instead of split-braining forever.

The test driver's own edge requests ride plain urllib (not the pooled
internal wire), so the observer is never partitioned from the nodes.
"""

import json
import socket
import urllib.error
import urllib.request

import pytest

from cluster_helpers import make_cluster, req, uri
from pilosa_tpu.parallel.cluster import (
    Cluster,
    DEAD_HEARTBEATS,
    Node,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import faults


@pytest.fixture(autouse=True)
def _fast_and_clean(monkeypatch):
    """Fresh plane per test + shrunken backoffs/timeouts so partitioned
    broadcasts and cleanup drains don't serialize test wall time."""
    faults.clear()
    monkeypatch.setattr(Cluster, "SEND_BACKOFF_S", 0.01)
    monkeypatch.setattr(Cluster, "CLEANUP_DRAIN_TIMEOUT", 1.0)
    yield
    faults.clear()


def boot(tmp_path, n, replica_n=1, **kw):
    """Install the fault plane FIRST so each server self-registers its
    name→endpoint mapping at open, then boot the cluster."""
    plane = faults.install()
    servers = make_cluster(tmp_path, n, replica_n=replica_n, **kw)
    return plane, servers


def seed(servers, n_shards=6):
    req("POST", f"{uri(servers[0])}/index/i", {})
    req("POST", f"{uri(servers[0])}/index/i/field/f", {})
    cols = [s * SHARD_WIDTH + 7 for s in range(n_shards)]
    req("POST", f"{uri(servers[0])}/index/i/field/f/import",
        {"rows": [1] * len(cols), "columns": cols})
    return cols


def names(servers):
    return [s.api.cluster.local.id for s in servers]


def heartbeat_rounds(servers, rounds):
    for _ in range(rounds):
        for s in servers:
            s.api.cluster.heartbeat()


def post_query(server, pql, expect_status=None):
    r = urllib.request.Request(
        f"{uri(server)}/index/i/query", data=pql, method="POST",
        headers={"Content-Type": "text/plain"},
    )
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = json.loads(e.read() or b"{}")
        if expect_status is not None:
            assert e.code == expect_status, (e.code, body)
        return e.code, body


class TestMinorityDegradation:
    def test_symmetric_partition_minority_read_only(self, tmp_path):
        """3 nodes, coordinator partitioned off: the minority side
        degrades (writes 503, locally-owned reads OK, membership
        intact, no resize) while the majority side performs a
        corroborated declare-dead + resize and keeps serving."""
        plane, servers = boot(tmp_path, 3, replica_n=2)
        try:
            cols = seed(servers)
            n0, n1, n2 = servers
            epoch_before = n1.api.cluster.epoch
            acted_before = list(n0.api.cluster.acted_epochs)
            plane.partition("n0", "n1")
            plane.partition("n0", "n2")

            heartbeat_rounds(servers, DEAD_HEARTBEATS)

            # minority (n0): degraded, membership INTACT, never acted
            assert n0.api.cluster.degraded is True
            assert set(n0.api.cluster.nodes) == {"n0", "n1", "n2"}
            assert list(n0.api.cluster.acted_epochs) == acted_before
            st = req("GET", f"{uri(n0)}/status")
            assert st["clusterDegraded"] is True
            # writes shed 503 with Retry-After
            status, body = post_query(n0, b"Set(3, f=9)",
                                      expect_status=503)
            assert "degraded" in body["error"]
            # a locally-owned shard still reads
            local_shard = next(
                s for s in range(6)
                if n0.api.cluster.owns_shard("i", s)
            )
            status, body = post_query(
                n0, f"Options(Count(Row(f=1)), shards=[{local_shard}])"
                .encode())
            assert status == 200 and body["results"] == [1]
            # a cluster-wide read needing unreachable owners → 503
            all_owned = all(n0.api.cluster.owns_shard("i", s)
                            for s in range(6))
            if not all_owned:
                status, body = post_query(n0, b"Count(Row(f=1))",
                                          expect_status=503)
                assert "degraded" in body["error"]

            # majority (n1/n2): declared n0 dead with corroboration,
            # epoch advanced, still serving full queries
            assert set(n1.api.cluster.nodes) == {"n1", "n2"}
            assert set(n2.api.cluster.nodes) == {"n1", "n2"}
            assert n1.api.cluster.epoch > epoch_before
            for s in (n1, n2):
                status, body = post_query(s, b"Count(Row(f=1))")
                assert status == 200 and body["results"] == [len(cols)]

            # heal: the evicted ex-coordinator detects the eviction and
            # rejoins instead of split-braining
            plane.heal()
            n0.api.cluster.heartbeat()
            assert n0.api.cluster.rejoins == 1
            assert n0.api.cluster.wait_until_normal(30)
            n1.api.cluster.coordinate_resize()  # drain join resize
            heartbeat_rounds(servers, 1)
            for s in servers:
                assert set(s.api.cluster.nodes) == {"n0", "n1", "n2"}, (
                    s.config.name)
                assert s.api.cluster.degraded is False
            status, body = post_query(n0, b"Count(Row(f=1))")
            assert status == 200 and body["results"] == [len(cols)]
        finally:
            for s in servers:
                s.close()

    def test_asymmetric_partition_no_minority_resize(self, tmp_path):
        """One-way partition (n0 cannot reach n1/n2, both can reach
        n0): pre-PR n0 declared both peers dead and ran a minority
        resize + cleanup; now its quorum probe rides the same dead
        outbound links, so it degrades read-only instead — and the
        majority, which still SEES n0 alive, never amputates it."""
        plane, servers = boot(tmp_path, 3, replica_n=1)
        try:
            seed(servers)
            n0, n1, n2 = servers
            acted_before = {s.config.name: len(s.api.cluster.acted_epochs)
                            for s in servers}
            plane.partition("n0", "n1", bidirectional=False)
            plane.partition("n0", "n2", bidirectional=False)

            heartbeat_rounds(servers, DEAD_HEARTBEATS + 1)

            # n0: suspects both peers but cannot act (no quorum) —
            # degraded read-only, zero coordinated actions
            assert n0.api.cluster.degraded is True
            assert set(n0.api.cluster.nodes) == {"n0", "n1", "n2"}
            assert (len(n0.api.cluster.acted_epochs)
                    == acted_before["n0"])
            assert n0.api.cluster.quorum_denials > 0
            post_query(n0, b"Set(9, f=9)", expect_status=503)
            # majority: n0 answers their probes, so nothing changed
            for s in (n1, n2):
                assert set(s.api.cluster.nodes) == {"n0", "n1", "n2"}
                assert s.api.cluster.degraded is False
            # no fragment was deleted anywhere without quorum
            for s in servers:
                for entry in s.api.cluster.cleanup_log:
                    assert not (entry["removed"] and not entry["quorum"])

            plane.heal()
            heartbeat_rounds(servers, 1)
            assert n0.api.cluster.degraded is False
            status, body = post_query(n0, b"Count(Row(f=1))")
            assert status == 200
        finally:
            for s in servers:
                s.close()

    def test_minority_pair_keeps_sole_copies(self, tmp_path):
        """5 nodes, replica_n=1, partition {n0,n1,n2} | {n3,n4}: pre-PR
        the minority pair elected its own coordinator, resized over a
        2-node ring, and the cleanup DELETED sole surviving copies by
        that minority view of ownership — permanent data loss. Now the
        pair lacks quorum: no resize, no deletion, and after heal +
        rejoin every acked bit is queryable cluster-wide again."""
        plane, servers = boot(tmp_path, 5, replica_n=1)
        try:
            cols = seed(servers, n_shards=10)
            minority = [s for s in servers
                        if s.config.name in ("n3", "n4")]
            majority = [s for s in servers
                        if s.config.name not in ("n3", "n4")]
            # fragments whose SOLE copy lives on the minority pair
            minority_frag_counts = {
                s.config.name: sum(
                    1 for sh in range(10)
                    if s.api.cluster.owns_shard("i", sh)
                ) for s in minority
            }
            for a in majority:
                for b in minority:
                    plane.partition(a.config.name, b.config.name)

            heartbeat_rounds(servers, DEAD_HEARTBEATS)

            # minority pair: degraded, membership intact, never resized
            for s in minority:
                assert s.api.cluster.degraded is True, s.config.name
                assert len(s.api.cluster.nodes) == 5, s.config.name
                assert not any(a for e, a in s.api.cluster.acted_epochs
                               if a.startswith("declare-dead"))
                # its sole copies SURVIVED (no minority-ring cleanup)
                held = sum(
                    1 for sh in range(10)
                    if (v := s.holder.index("i").field("f")
                        .view("standard")) and v.fragment(sh) is not None
                    and v.fragment(sh).count() > 0
                )
                assert held >= minority_frag_counts[s.config.name], (
                    s.config.name)
                for entry in s.api.cluster.cleanup_log:
                    assert not (entry["removed"] and not entry["quorum"])
            # majority: declared the pair dead (it holds 3/5 = quorum)
            for s in majority:
                assert set(s.api.cluster.nodes) == {"n0", "n1", "n2"}, (
                    s.config.name)

            # heal → the evicted pair rejoins → full coverage returns
            plane.heal()
            for s in minority:
                s.api.cluster.heartbeat()
                assert s.api.cluster.rejoins == 1, s.config.name
                assert s.api.cluster.wait_until_normal(30)
            majority[0].api.cluster.coordinate_resize()  # drain joins
            heartbeat_rounds(servers, 1)
            for s in servers:
                assert len(s.api.cluster.nodes) == 5, s.config.name
            status, body = post_query(servers[0], b"Count(Row(f=1))")
            assert status == 200 and body["results"] == [len(cols)]
        finally:
            for s in servers:
                s.close()


class TestCorroboratedDeath:
    def test_single_observer_flap_cannot_amputate(self, tmp_path):
        """Only the coordinator's link to n2 is cut: n1 still reaches
        n2, so the suspect-probe corroboration vetoes the death — the
        pre-PR single-observer detector amputated a live node here.
        Cutting n1's link too completes the corroboration and the
        (now genuinely unreachable) node is declared dead."""
        plane, servers = boot(tmp_path, 3, replica_n=2)
        try:
            seed(servers)
            n0, n1, n2 = servers
            plane.partition("n0", "n2", bidirectional=False)
            heartbeat_rounds([n0], DEAD_HEARTBEATS)
            assert set(n0.api.cluster.nodes) == {"n0", "n1", "n2"}
            assert n0.api.cluster.deaths_vetoed >= 1
            assert n0.api.cluster.deaths_declared == 0

            plane.partition("n1", "n2", bidirectional=False)
            n0.api.cluster.heartbeat()
            assert n0.api.cluster.deaths_declared == 1
            assert set(n0.api.cluster.nodes) == {"n0", "n1"}
            assert set(n1.api.cluster.nodes) == {"n0", "n1"}
        finally:
            for s in servers:
                s.close()

    def test_two_node_cluster_survivor_may_act(self, tmp_path):
        """2-node special case (documented tradeoff): all-but-self
        corroboration is vacuous and a majority of 2 is unreachable by
        definition, so the survivor is allowed to fail over alone —
        the reference has the same n=2 blind spot."""
        plane, servers = boot(tmp_path, 2, replica_n=2)
        try:
            seed(servers)
            n0, n1 = servers
            victim = n1
            victim.close()
            for _ in range(DEAD_HEARTBEATS):
                n0.api.cluster.heartbeat()
            assert set(n0.api.cluster.nodes) == {"n0"}
            assert n0.api.cluster.deaths_declared == 1
            assert n0.api.cluster.degraded is False
            status, body = post_query(n0, b"Count(Row(f=1))")
            assert status == 200
        finally:
            for s in servers:
                if s is not victim:
                    s.close()


class TestEpochFencing:
    def test_stale_epoch_messages_rejected(self, tmp_path):
        """Fenced control messages stamped with an epoch below the
        receiver's are rejected unapplied: state commands can't re-gate
        or un-gate, cleanup can't delete, instructions can't re-fetch."""
        plane, servers = boot(tmp_path, 2, replica_n=1)
        try:
            seed(servers)
            n0 = servers[0]
            cluster = n0.api.cluster
            cluster.adopt_epoch(cluster.epoch + 5)
            current = cluster.epoch
            rejects = cluster.stale_epoch_rejects

            out = cluster.handle_message(
                {"type": "cluster-state", "state": "RESIZING",
                 "epoch": current - 1})
            assert "stale epoch" in out.get("error", "")
            assert cluster.state == "NORMAL"  # not re-gated
            out = cluster.handle_message(
                {"type": "node-leave", "id": "n1", "epoch": current - 3})
            assert "stale epoch" in out.get("error", "")
            assert "n1" in cluster.nodes  # membership untouched
            out = cluster.handle_message(
                {"type": "resize-cleanup",
                 "members": sorted(cluster.nodes),
                 "epoch": current - 1})
            assert "stale epoch" in out.get("error", "")
            assert cluster.stale_epoch_rejects == rejects + 3

            # equal and newer epochs pass (and newer is adopted)
            out = cluster.handle_message(
                {"type": "cluster-state", "state": "NORMAL",
                 "epoch": current})
            assert "error" not in out
            cluster.handle_message(
                {"type": "cluster-state", "state": "NORMAL",
                 "epoch": current + 4})
            assert cluster.epoch == current + 4
        finally:
            for s in servers:
                s.close()

    def test_stale_cleanup_cannot_delete(self, tmp_path):
        """A resize-cleanup minted before the partition must not delete
        fragments after the epoch moved on — even when the membership
        list it carries matches."""
        import numpy as np

        plane, servers = boot(tmp_path, 2, replica_n=1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            n0 = servers[0]
            cluster = n0.api.cluster
            # a fragment n0 does NOT own, planted on BOTH nodes (the
            # owner holds identical content, so only the epoch fence —
            # not the owner-coverage guard — stands between the stale
            # message and the deletion)
            shard = next(s for s in range(64)
                         if not cluster.owns_shard("i", s))
            for s in servers:
                f = s.holder.index("i").field("f")
                f.view("standard", create=True).fragment(
                    shard, create=True
                ).bulk_import(np.asarray([1], np.uint64),
                              np.asarray([2], np.uint64))
            members = sorted(cluster.nodes)
            stale = cluster.epoch
            cluster.adopt_epoch(stale + 2)  # a later coordinator acted

            out = cluster.handle_message(
                {"type": "resize-cleanup", "members": members,
                 "epoch": stale})
            assert "stale epoch" in out.get("error", "")
            v = n0.holder.index("i").field("f").view("standard")
            assert v.fragment(shard) is not None  # survived

            # the SAME message at the current epoch does delete
            out = cluster.handle_message(
                {"type": "resize-cleanup", "members": members,
                 "epoch": cluster.epoch})
            assert "error" not in out
            assert v.fragment(shard) is None
        finally:
            for s in servers:
                s.close()

    def test_cleanup_defers_until_owner_absorbed(self, tmp_path):
        """The owner-coverage guard: cleanup must NOT delete a
        non-owned copy holding bits no owner has (an acked write from
        an older ring) — it defers, an anti-entropy pass absorbs the
        stray copy into the owner, and only then does cleanup delete."""
        import numpy as np

        plane, servers = boot(tmp_path, 2, replica_n=1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            n0 = servers[0]
            cluster = n0.api.cluster
            shard = next(s for s in range(64)
                         if not cluster.owns_shard("i", s))
            owner = next(s for s in servers
                         if s.api.cluster.owns_shard("i", shard))
            assert owner is not n0
            f = n0.holder.index("i").field("f")
            f.view("standard", create=True).fragment(
                shard, create=True
            ).bulk_import(np.asarray([3], np.uint64),
                          np.asarray([7], np.uint64))

            removed = cluster.cleanup_unowned(sorted(cluster.nodes))
            v = n0.holder.index("i").field("f").view("standard")
            assert removed == 0 and v.fragment(shard) is not None
            assert cluster.cleanup_log[-1]["deferred"] == 1

            # the owner's sync pass absorbs the stray copy...
            owner.api.cluster.sync_holder()
            of = (owner.holder.index("i").field("f")
                  .view("standard").fragment(shard))
            assert of is not None and of.contains(3, 7)
            # ...and the next cleanup deletes the now-covered copy
            removed = cluster.cleanup_unowned(sorted(cluster.nodes))
            assert removed == 1
            assert v.fragment(shard) is None
        finally:
            for s in servers:
                s.close()

    def test_healed_ex_coordinator_is_fenced_then_rejoins(self, tmp_path):
        """End to end: partition the coordinator away, let the majority
        declare it dead (epoch E+…), heal, and verify (a) the
        ex-coordinator's pre-partition-epoch commands bounce off every
        peer, (b) its own next coordinated action adopts the higher
        epoch first (no stale acting), (c) its heartbeat detects the
        eviction and rejoins."""
        plane, servers = boot(tmp_path, 3, replica_n=2)
        try:
            seed(servers)
            n0, n1, n2 = servers
            plane.partition("n0", "n1")
            plane.partition("n0", "n2")
            heartbeat_rounds(servers, DEAD_HEARTBEATS)
            assert set(n1.api.cluster.nodes) == {"n1", "n2"}
            stale_epoch = n0.api.cluster.epoch
            assert n1.api.cluster.epoch > stale_epoch

            plane.heal()
            # the ex-coordinator's stale commands (minted before the
            # partition) arrive AFTER the heal — all fenced
            for message in (
                {"type": "cluster-state", "state": "RESIZING",
                 "epoch": stale_epoch},
                {"type": "resize-cleanup",
                 "members": sorted(n1.api.cluster.nodes),
                 "epoch": stale_epoch},
            ):
                out = n1.api.cluster.handle_message(dict(message))
                assert "stale epoch" in out.get("error", ""), message
            assert n1.api.cluster.state == "NORMAL"

            # its next real action adopts the majority's epoch first:
            # check_quorum probes peers, adopts, then mints ABOVE it
            n0.api.cluster.coordinate_resize()
            assert n0.api.cluster.epoch > n1.api.cluster.epoch - 1

            n0.api.cluster.heartbeat()
            assert n0.api.cluster.rejoins == 1
            assert n0.api.cluster.wait_until_normal(30)
            heartbeat_rounds(servers, 1)
            for s in servers:
                assert set(s.api.cluster.nodes) == {"n0", "n1", "n2"}
        finally:
            for s in servers:
                s.close()

    def test_epoch_persists_across_restart(self, tmp_path):
        """The persisted high-water mark stops a RESTARTED node from
        reusing pre-crash epochs."""
        plane, servers = boot(tmp_path, 1)
        try:
            cluster = servers[0].api.cluster
            cluster.adopt_epoch(41)
            data_dir = servers[0].config.data_dir
            servers[0].close()
            from pilosa_tpu.server import Server, ServerConfig

            reborn = Server(ServerConfig(
                data_dir=data_dir, port=0, name="n0",
                anti_entropy_interval=0, heartbeat_interval=0,
                use_mesh=False,
            )).open()
            servers = [reborn]
            assert reborn.api.cluster.epoch == 41
        finally:
            for s in servers:
                s.close()


class TestHeartbeatIsolation:
    def test_hung_peer_does_not_stall_detection(self, tmp_path):
        """A peer whose socket accepts but never answers must cost one
        tight heartbeat-timeout, not the 30 s client default — and the
        OTHER peers' probes (concurrent) still land in the same pass."""
        import time

        plane, servers = boot(tmp_path, 2)
        try:
            n0 = servers[0]
            tarpit = socket.socket()
            tarpit.bind(("localhost", 0))
            tarpit.listen(8)
            port = tarpit.getsockname()[1]
            n0.api.cluster.nodes["zz-tarpit"] = Node(
                "zz-tarpit", f"http://localhost:{port}")
            n0.api.cluster.heartbeat_timeout = 0.4
            t0 = time.monotonic()
            n0.api.cluster.heartbeat()
            wall = time.monotonic() - t0
            assert wall < 5.0, f"heartbeat stalled {wall:.1f}s on tarpit"
            states = {n.id: n.state
                      for n in n0.api.cluster.nodes.values()}
            assert states["zz-tarpit"] == "DEGRADED"
            assert states["n1"] == "NORMAL"  # probed despite the tarpit
            tarpit.close()
        finally:
            for s in servers:
                s.close()


class TestControlSendRetry:
    def test_send_retry_rides_out_one_drop(self, tmp_path):
        """A single dropped control send succeeds on retry; a hard
        partition still fails after the bounded attempts."""
        plane, servers = boot(tmp_path, 2)
        try:
            cluster = servers[0].api.cluster
            peer_uri = servers[1].api.cluster.local.uri
            plane.add("drop", src="n0", dst="n1",
                      route="/internal/cluster/message", count=1)
            out = cluster._send_retry(
                peer_uri, {"type": "create-shard", "index": "x",
                           "shards": [1]})
            assert out == {}
            assert plane.dropped == 1
            from pilosa_tpu.parallel.client import ClientError

            plane.add("drop", src="n0", dst="n1")
            with pytest.raises(ClientError):
                cluster._send_retry(
                    peer_uri, {"type": "create-shard", "index": "x",
                               "shards": [2]})
        finally:
            for s in servers:
                s.close()

    def test_state_broadcast_survives_flaky_link(self, tmp_path):
        """End to end: the NORMAL broadcast's first attempt is dropped;
        without retry the peer would sit RESIZING until the straggler
        timeout — with it, the resize leaves everyone NORMAL."""
        plane, servers = boot(tmp_path, 2, replica_n=2)
        try:
            seed(servers, n_shards=2)
            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            peer = next(s for s in servers if s is not coord)
            # drop exactly one message-delivery attempt per direction
            # pair during the resize
            plane.add("drop", src=coord.config.name,
                      dst=peer.config.name,
                      route="/internal/cluster/message", count=1)
            coord.api.cluster.coordinate_resize()
            assert peer.api.cluster.state == "NORMAL"
            assert coord.api.cluster.state == "NORMAL"
        finally:
            for s in servers:
                s.close()


class TestChaosHarness:
    def test_quick_chaos_schedule_passes_oracles(self, tmp_path):
        """One seeded schedule end to end through the harness the bench
        gate uses: randomized partition/kill/heal under load, then the
        four oracles (zero lost acked writes, no non-quorum deletion,
        ≤1 coordinator per epoch, byte-identical replicas)."""
        faults.clear()  # the harness installs its own plane
        from pilosa_tpu.testing.chaos import run_chaos

        out = run_chaos(tmp_path, n_schedules=1, n_events=5, seed=3)
        assert out["ok"], out
        assert out["unconverged"] == 0
        assert out["acked_writes_total"] > 0

    @pytest.mark.slow
    def test_chaos_soak(self, tmp_path):
        """Long randomized soak (env-tunable): more schedules, more
        events, 5 nodes — the ≥20-schedule acceptance gate also runs in
        bench_suite's `chaos` config with its record in
        BENCH_SUITE.json."""
        import os

        faults.clear()
        from pilosa_tpu.testing.chaos import run_chaos

        out = run_chaos(
            tmp_path,
            n_schedules=int(os.environ.get("PILOSA_TPU_CHAOS_SCHEDULES",
                                           "12")),
            n_nodes=int(os.environ.get("PILOSA_TPU_CHAOS_NODES", "5")),
            n_events=int(os.environ.get("PILOSA_TPU_CHAOS_EVENTS", "8")),
            seed=int(os.environ.get("PILOSA_TPU_CHAOS_SEED", "1")),
        )
        assert out["ok"], out
        assert out["unconverged"] == 0


class TestObservabilitySurface:
    def test_cluster_series_and_status(self, tmp_path):
        plane, servers = boot(tmp_path, 2)
        try:
            st = req("GET", f"{uri(servers[0])}/status")
            assert "epoch" in st and "clusterDegraded" in st
            metrics = req("GET", f"{uri(servers[0])}/metrics", raw=True)
            text = metrics.decode()
            for series in ("cluster_epoch", "cluster_quorum",
                           "cluster_degraded",
                           "cluster_heartbeat_probes_total",
                           "cluster_stale_epoch_rejects_total"):
                assert f"pilosa_tpu_{series}" in text, series
            snap = req("GET", f"{uri(servers[0])}/debug/vars")
            assert "cluster" in snap
            assert snap["cluster"]["cluster_members"] == 2
        finally:
            for s in servers:
                s.close()

    def test_degraded_write_shed_counts_on_qos_path(self, tmp_path):
        plane, servers = boot(tmp_path, 1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            servers[0].api.cluster.degraded = True
            post_query(servers[0], b"Set(1, f=1)", expect_status=503)
            from pilosa_tpu.utils.stats import global_stats

            snap = global_stats().snapshot()
            tagged = [k for k in snap.get("counters", {})
                      if "qos_shed" in k and "cluster_degraded" in k]
            assert tagged, snap.get("counters")
        finally:
            servers[0].api.cluster.degraded = False
            for s in servers:
                s.close()
