

class TestRowsContaining:
    def test_matches_per_row_contains(self, tmp_path):
        import numpy as np

        from pilosa_tpu.storage.fragment import Fragment

        frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
        rng = np.random.default_rng(11)
        # mixed container kinds: sparse rows (array), a dense run row, a
        # bitmap-container row
        rows, cols = [], []
        for r in range(40):
            n = 50 if r % 3 else 6000
            rows.append(np.full(n, r, np.uint64))
            cols.append(rng.integers(0, 1 << 20, n, dtype=np.uint64))
        rows.append(np.full(70000, 40, np.uint64))
        cols.append(np.arange(70000, dtype=np.uint64))  # run containers
        frag.bulk_import(np.concatenate(rows), np.concatenate(cols))

        for pos in [0, 1, 77, 65535, 65536, 69999, 70000, (1 << 20) - 1,
                    int(cols[0][0]), int(cols[3][0])]:
            want = sorted(
                r for r in frag.row_ids() if frag.contains(r, pos)
            )
            assert sorted(frag.rows_containing(pos)) == want, pos
        frag.close()

    def test_contains_low_all_kinds(self):
        import numpy as np

        from pilosa_tpu.roaring.bitmap import Container

        # array
        c = Container.from_lows(np.asarray([3, 9, 1000], np.uint16))
        assert c.contains_low(9) and not c.contains_low(8)
        # run
        c = Container.from_lows(np.arange(100, 4200, dtype=np.uint16))
        assert c.kind == 3 and c.contains_low(100) and c.contains_low(4199)
        assert not c.contains_low(99) and not c.contains_low(4200)
        # bitmap
        lows = np.unique(
            np.random.default_rng(0).integers(0, 65536, 8000).astype(np.uint16)
        )
        c = Container.from_lows(lows)
        assert c.kind == 2
        s = set(lows.tolist())
        for v in [0, 1, 17, 65535, int(lows[0]), int(lows[-1])]:
            assert c.contains_low(v) == (v in s)
        # empty
        c = Container.from_lows(np.empty(0, np.uint16))
        assert not c.contains_low(0)


class TestRowCountsMemo:
    def test_row_counts_memoized_and_invalidated_by_writes(self, tmp_path):
        import numpy as np

        from pilosa_tpu.storage.fragment import Fragment

        frag = Fragment(str(tmp_path / "f"), "i", "f", "standard", 0).open()
        frag.bulk_import(np.asarray([1, 1, 2], np.uint64),
                         np.asarray([10, 20, 30], np.uint64))
        rows, counts = frag.row_counts()
        assert rows.tolist() == [1, 2] and counts.tolist() == [2, 1]
        # memo hit: identical object back while unmutated
        assert frag.row_counts()[0] is rows
        # any write invalidates: a NEW row must appear
        frag.set_bit(7, 40)
        rows2, counts2 = frag.row_counts()
        assert rows2.tolist() == [1, 2, 7]
        assert counts2.tolist() == [2, 1, 1]
        # clears too
        frag.clear_bit(7, 40)
        assert frag.row_counts()[0].tolist() == [1, 2]
        frag.close()
