"""Executor behavioral tests: PQL strings against a single in-process node
(the bulk of the reference's coverage — executor_test.go style per
SURVEY.md §4), with numpy/python set oracles."""

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import PQLError
from pilosa_tpu.executor.result import GroupCount, Pair, RowResult, ValCount
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import FieldOptions, Holder


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    yield holder, Executor(holder)
    holder.close()


def setup_stars(holder):
    """Star-Trace-like dataset (BASELINE config #1): stargazer rows over
    repo columns, language as second field, spanning two shards."""
    idx = holder.create_index("repos")
    stargazer = idx.create_field("stargazer")
    language = idx.create_field("language")
    s2 = SHARD_WIDTH  # a column in shard 1
    data = {
        1: [10, 20, 30, s2 + 1],
        2: [20, 30, 40],
        3: [s2 + 1, s2 + 2],
    }
    for row, cols in data.items():
        for c in cols:
            stargazer.set_bit(row, c)
    langs = {5: [10, 20, s2 + 1], 6: [30, 40, s2 + 2]}
    for row, cols in langs.items():
        for c in cols:
            language.set_bit(row, c)
    all_cols = {c for cols in data.values() for c in cols} | {
        c for cols in langs.values() for c in cols
    }
    idx.mark_columns_exist(sorted(all_cols))
    return idx, data, langs


class TestBitmapCalls:
    def test_row(self, env):
        holder, ex = env
        _, data, _ = setup_stars(holder)
        (res,) = ex.execute("repos", "Row(stargazer=1)")
        assert res.columns().tolist() == data[1]

    def test_union_intersect_difference_xor(self, env):
        holder, ex = env
        _, data, _ = setup_stars(holder)
        s1, s2, s3 = (set(data[i]) for i in (1, 2, 3))
        cases = {
            "Union(Row(stargazer=1), Row(stargazer=2))": s1 | s2,
            "Intersect(Row(stargazer=1), Row(stargazer=2))": s1 & s2,
            "Difference(Row(stargazer=1), Row(stargazer=2))": s1 - s2,
            "Xor(Row(stargazer=1), Row(stargazer=2))": s1 ^ s2,
            "Union(Row(stargazer=1), Row(stargazer=2), Row(stargazer=3))": s1 | s2 | s3,
        }
        for pql, want in cases.items():
            (res,) = ex.execute("repos", pql)
            assert res.columns().tolist() == sorted(want), pql

    def test_count_fused(self, env):
        holder, ex = env
        _, data, langs = setup_stars(holder)
        (n,) = ex.execute(
            "repos", "Count(Intersect(Row(stargazer=1), Row(language=5)))"
        )
        assert n == len(set(data[1]) & set(langs[5]))

    def test_not_and_all(self, env):
        holder, ex = env
        _, data, langs = setup_stars(holder)
        universe = {c for cols in data.values() for c in cols} | {
            c for cols in langs.values() for c in cols
        }
        (res,) = ex.execute("repos", "Not(Row(stargazer=1))")
        assert res.columns().tolist() == sorted(universe - set(data[1]))
        (res,) = ex.execute("repos", "All()")
        assert res.columns().tolist() == sorted(universe)

    def test_shift(self, env):
        holder, ex = env
        _, data, _ = setup_stars(holder)
        (res,) = ex.execute("repos", "Shift(Row(stargazer=2), n=3)")
        assert res.columns().tolist() == [c + 3 for c in data[2]]

    def test_empty_row(self, env):
        holder, ex = env
        setup_stars(holder)
        (res,) = ex.execute("repos", "Row(stargazer=99)")
        assert res.columns().size == 0
        (n,) = ex.execute("repos", "Count(Row(stargazer=99))")
        assert n == 0


class TestWrites:
    def test_set_clear(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        assert ex.execute("i", "Set(10, f=1)") == [True]
        assert ex.execute("i", "Set(10, f=1)") == [False]
        (res,) = ex.execute("i", "Row(f=1)")
        assert res.columns().tolist() == [10]
        assert ex.execute("i", "Clear(10, f=1)") == [True]
        (res,) = ex.execute("i", "Row(f=1)")
        assert res.columns().size == 0

    def test_set_marks_existence(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        ex.execute("i", "Set(7, f=1) Set(9, f=2)")
        (res,) = ex.execute("i", "All()")
        assert res.columns().tolist() == [7, 9]

    def test_clear_row_and_store(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        ex.execute("i", "Set(1, f=1) Set(2, f=1) Set(3, f=2)")
        ex.execute("i", "Store(Row(f=1), f=9)")
        (res,) = ex.execute("i", "Row(f=9)")
        assert res.columns().tolist() == [1, 2]
        assert ex.execute("i", "ClearRow(f=1)") == [True]
        (res,) = ex.execute("i", "Row(f=1)")
        assert res.columns().size == 0
        # stored row unaffected
        (res,) = ex.execute("i", "Row(f=9)")
        assert res.columns().tolist() == [1, 2]

    def test_v0_aliases_execute(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        assert ex.execute("i", "SetBit(5, f=1)") == [True]
        (res,) = ex.execute("i", "Bitmap(f=1)")
        assert res.columns().tolist() == [5]


class TestBSI:
    def setup_fares(self, holder):
        idx = holder.create_index("taxi")
        fare = idx.create_field(
            "fare", FieldOptions(type="int", min=-50, max=500)
        )
        self.values = {0: -50, 1: 0, 2: 10, 3: 11, 4: 499, 5: 500,
                       SHARD_WIDTH + 7: 42}
        for col, v in self.values.items():
            fare.set_value(col, v)
        idx.mark_columns_exist(sorted(self.values))
        return idx

    @pytest.mark.parametrize(
        "op,py",
        [("<", lambda v, p: v < p), ("<=", lambda v, p: v <= p),
         (">", lambda v, p: v > p), (">=", lambda v, p: v >= p),
         ("==", lambda v, p: v == p), ("!=", lambda v, p: v != p)],
    )
    @pytest.mark.parametrize("pred", [-51, -50, 0, 10, 42, 500, 501])
    def test_range_ops(self, env, op, py, pred):
        holder, ex = env
        self.setup_fares(holder)
        (res,) = ex.execute("taxi", f"Range(fare {op} {pred})")
        want = sorted(c for c, v in self.values.items() if py(v, pred))
        assert res.columns().tolist() == want, f"fare {op} {pred}"

    def test_between(self, env):
        holder, ex = env
        self.setup_fares(holder)
        (res,) = ex.execute("taxi", "Range(fare >< [0, 42])")
        want = sorted(c for c, v in self.values.items() if 0 <= v <= 42)
        assert res.columns().tolist() == want

    @pytest.mark.parametrize(
        "op,py",
        [("<", lambda v, p: v < p), ("<=", lambda v, p: v <= p),
         (">", lambda v, p: v > p), (">=", lambda v, p: v >= p),
         ("==", lambda v, p: v == p), ("!=", lambda v, p: v != p)],
    )
    @pytest.mark.parametrize("pred", [-50.5, 0.5, 10.5, 499.5])
    def test_range_fractional_predicate(self, env, op, py, pred):
        # Stored values are integers; a fractional predicate must map onto
        # the integer lattice exactly (x < 10.5 ⇔ x <= 10, never x < 10).
        holder, ex = env
        self.setup_fares(holder)
        (res,) = ex.execute("taxi", f"Range(fare {op} {pred})")
        want = sorted(c for c, v in self.values.items() if py(v, pred))
        assert res.columns().tolist() == want, f"fare {op} {pred}"

    def test_range_huge_predicate(self, env):
        # Predicates beyond float range must hit the out-of-range clamp,
        # not crash (float(10**400) raises OverflowError).
        holder, ex = env
        self.setup_fares(holder)
        huge = 10 ** 400
        (res,) = ex.execute("taxi", f"Range(fare < {huge})")
        assert res.columns().tolist() == sorted(self.values)
        (res,) = ex.execute("taxi", f"Range(fare > {huge})")
        assert res.columns().tolist() == []

    def test_range_infinite_fractional_predicate(self, env):
        # A ~330-digit literal WITH a fractional part parses to float
        # +/-inf; math.floor(inf) would raise, so the inf clamp must
        # short-circuit to universe/empty.
        holder, ex = env
        self.setup_fares(holder)
        big = "9" * 330 + ".5"
        every = sorted(self.values)
        for op, want in (("<", every), ("<=", every), (">", []), (">=", []),
                         ("==", []), ("!=", every)):
            (res,) = ex.execute("taxi", f"Range(fare {op} {big})")
            assert res.columns().tolist() == want, f"fare {op} inf"
        for op, want in (("<", []), ("<=", []), (">", every), (">=", every)):
            (res,) = ex.execute("taxi", f"Range(fare {op} -{big})")
            assert res.columns().tolist() == want, f"fare {op} -inf"

    def test_between_fractional(self, env):
        holder, ex = env
        self.setup_fares(holder)
        (res,) = ex.execute("taxi", "Range(fare >< [0.5, 42.5])")
        want = sorted(c for c, v in self.values.items() if 0.5 <= v <= 42.5)
        assert res.columns().tolist() == want

    def test_row_condition_alias(self, env):
        holder, ex = env
        self.setup_fares(holder)
        # v1.3+ allows Row(fare > 10) as alias for Range
        (res,) = ex.execute("taxi", "Row(fare > 10)")
        want = sorted(c for c, v in self.values.items() if v > 10)
        assert res.columns().tolist() == want

    def test_sum_min_max(self, env):
        holder, ex = env
        self.setup_fares(holder)
        vals = self.values
        (s,) = ex.execute("taxi", 'Sum(field="fare")')
        assert (s.value, s.count) == (sum(vals.values()), len(vals))
        (mn,) = ex.execute("taxi", 'Min(field="fare")')
        assert (mn.value, mn.count) == (-50, 1)
        (mx,) = ex.execute("taxi", 'Max(field="fare")')
        assert (mx.value, mx.count) == (500, 1)

    def test_sum_with_filter(self, env):
        holder, ex = env
        self.setup_fares(holder)
        (s,) = ex.execute("taxi", 'Sum(Range(fare > 0), field="fare")')
        want = [v for v in self.values.values() if v > 0]
        assert (s.value, s.count) == (sum(want), len(want))

    def test_min_max_tie_counts(self, env):
        holder, ex = env
        idx = holder.create_index("t2")
        f = idx.create_field("v", FieldOptions(type="int", min=0, max=10))
        for col, v in [(0, 3), (1, 3), (2, 7)]:
            f.set_value(col, v)
        (mn,) = ex.execute("t2", 'Min(field="v")')
        assert (mn.value, mn.count) == (3, 2)

    def test_empty_aggregate(self, env):
        holder, ex = env
        idx = holder.create_index("t3")
        idx.create_field("v", FieldOptions(type="int", min=0, max=10))
        (s,) = ex.execute("t3", 'Sum(field="v")')
        assert (s.value, s.count) == (0, 0)
        (mn,) = ex.execute("t3", 'Min(field="v")')
        assert (mn.value, mn.count) == (0, 0)


class TestTopNRowsGroupBy:
    def setup_ranked(self, holder):
        idx = holder.create_index("r")
        f = idx.create_field("f")
        g = idx.create_field("g")
        counts = {1: 5, 2: 50, 3: 20, 4: 35}
        for row, n in counts.items():
            for c in range(n):
                f.set_bit(row, c)
        # second shard contribution for row 3
        for c in range(15):
            f.set_bit(3, SHARD_WIDTH + c)
        for c in range(0, 60, 2):
            g.set_bit(7, c)
        cols = set(range(60)) | {SHARD_WIDTH + c for c in range(15)}
        idx.mark_columns_exist(sorted(cols))
        return idx

    def test_topn(self, env):
        holder, ex = env
        self.setup_ranked(holder)
        (pairs,) = ex.execute("r", "TopN(f, n=3)")
        assert [(p.id, p.count) for p in pairs] == [(2, 50), (3, 35), (4, 35)]

    def test_topn_with_filter(self, env):
        holder, ex = env
        self.setup_ranked(holder)
        (pairs,) = ex.execute("r", "TopN(f, Row(g=7), n=2)")
        # row2 ∩ evens<60: 25; row4 ∩ evens<60 (g covers 0..58): 18
        assert (pairs[0].id, pairs[0].count) == (2, 25)

    def test_topn_explicit_ids(self, env):
        holder, ex = env
        self.setup_ranked(holder)
        (pairs,) = ex.execute("r", "TopN(f, ids=[1, 3], n=5)")
        assert [(p.id, p.count) for p in pairs] == [(3, 35), (1, 5)]

    def test_rows(self, env):
        holder, ex = env
        self.setup_ranked(holder)
        assert ex.execute("r", "Rows(f)") == [[1, 2, 3, 4]]
        assert ex.execute("r", "Rows(f, limit=2)") == [[1, 2]]
        assert ex.execute("r", "Rows(f, previous=2)") == [[3, 4]]
        assert ex.execute("r", "Rows(f, column=40)") == [[2]]  # only row2 ⊇ 40

    def test_groupby(self, env):
        holder, ex = env
        self.setup_ranked(holder)
        (groups,) = ex.execute("r", "GroupBy(Rows(f), Rows(g))")
        got = {
            tuple(e["rowID"] for e in g.group): g.count for g in groups
        }
        # row1 (0..4) ∩ evens<60 = {0,2,4} → 3; row2 (0..49) ∩ evens → 25
        assert got[(1, 7)] == 3
        assert got[(2, 7)] == 25
        assert got[(4, 7)] == 18
        (groups,) = ex.execute("r", "GroupBy(Rows(f), Rows(g), limit=2)")
        assert len(groups) == 2

    def test_groupby_filter(self, env):
        holder, ex = env
        self.setup_ranked(holder)
        (groups,) = ex.execute(
            "r", "GroupBy(Rows(f), filter=Row(g=7))"
        )
        got = {g.group[0]["rowID"]: g.count for g in groups}
        assert got[1] == 3 and got[2] == 25

    def test_topn_threshold(self, env):
        """TopN(threshold=) — SURVEY-LOW surface (Appendix B: exact
        upstream semantics unverifiable, mount empty). Conservative
        reading under test: a minimum-global-count filter applied after
        the exact phase-2 recount, before trimming to n."""
        holder, ex = env
        self.setup_ranked(holder)
        # counts: row2=50, row3=35, row4=35, row1=5
        (pairs,) = ex.execute("r", "TopN(f, n=10, threshold=35)")
        assert [(p.id, p.count) for p in pairs] == [(2, 50), (3, 35), (4, 35)]
        (pairs,) = ex.execute("r", "TopN(f, n=10, threshold=36)")
        assert [(p.id, p.count) for p in pairs] == [(2, 50)]
        # threshold composes with n (filter first, then trim)
        (pairs,) = ex.execute("r", "TopN(f, n=1, threshold=35)")
        assert [(p.id, p.count) for p in pairs] == [(2, 50)]
        # explicit-ids recount respects the floor too
        (pairs,) = ex.execute("r", "TopN(f, ids=[1, 3], n=5, threshold=10)")
        assert [(p.id, p.count) for p in pairs] == [(3, 35)]

    def test_groupby_having_count(self, env):
        """GroupBy(having=Condition(count <op> N)) — SURVEY-LOW surface
        (Appendix B). Conservative reading under test: one condition on
        the merged group count, applied before limit."""
        holder, ex = env
        self.setup_ranked(holder)
        # base counts: (1,7)=3 (2,7)=25 (3,7)=10 (4,7)=18
        (groups,) = ex.execute(
            "r", "GroupBy(Rows(f), Rows(g), having=Condition(count > 10))"
        )
        got = {g.group[0]["rowID"]: g.count for g in groups}
        assert got == {2: 25, 4: 18}
        (groups,) = ex.execute(
            "r", "GroupBy(Rows(f), Rows(g), having=Condition(count >< [3, 18]))"
        )
        assert {g.group[0]["rowID"] for g in groups} == {1, 3, 4}
        # having applies BEFORE limit: the one survivor is returned even
        # though it sorts after the groups having filtered out
        (groups,) = ex.execute(
            "r",
            "GroupBy(Rows(f), Rows(g), limit=1, having=Condition(count == 18))",
        )
        assert [(g.group[0]["rowID"], g.count) for g in groups] == [(4, 18)]
        # float thresholds must not truncate: count < 3.5 keeps the
        # count==3 group (int(3.5) → "< 3" would drop it) — ADVICE r4
        (groups,) = ex.execute(
            "r", "GroupBy(Rows(f), Rows(g), having=Condition(count < 3.5))"
        )
        assert {g.group[0]["rowID"]: g.count for g in groups} == {1: 3}
        (groups,) = ex.execute(
            "r", "GroupBy(Rows(f), Rows(g), having=Condition(count >< [3.0, 10.5]))"
        )
        assert {g.group[0]["rowID"] for g in groups} == {1, 3}

    def test_condition_value_coercion(self):
        """Quoted numeric thresholds coerce; junk raises PQLError (not a
        bare TypeError that would 500 at the HTTP layer)."""
        from pilosa_tpu.executor.executor import PQLError, condition_test
        from pilosa_tpu.pql.ast import Condition

        assert condition_test(Condition(">", "5"), 6)
        assert not condition_test(Condition(">", "5"), 5)
        assert condition_test(Condition("<", "1.5"), 1)
        assert condition_test(Condition("><", ["3", "10.5"]), 10)
        with pytest.raises(PQLError, match="not numeric"):
            condition_test(Condition(">", "abc"), 1)

    def test_groupby_having_sum_requires_aggregate(self, env):
        from pilosa_tpu.executor.executor import PQLError

        holder, ex = env
        self.setup_ranked(holder)
        with pytest.raises(PQLError, match="aggregate"):
            ex.execute(
                "r", "GroupBy(Rows(f), having=Condition(sum > 10))"
            )
        with pytest.raises(PQLError, match="count or sum"):
            ex.execute(
                "r", "GroupBy(Rows(f), having=Condition(bogus > 10))"
            )
        with pytest.raises(PQLError, match="Condition"):
            ex.execute("r", "GroupBy(Rows(f), having=5)")

    def test_groupby_having_sum(self, env):
        holder, ex = env
        idx = holder.create_index("hs")
        f = idx.create_field("f")
        amt = idx.create_field("amt", FieldOptions(type="int", min=0, max=100))
        # group 1: cols 0..4 value 10 (sum 50); group 2: cols 5..6 value 40 (sum 80)
        for c in range(5):
            f.set_bit(1, c)
            amt.set_value(c, 10)
        for c in range(5, 7):
            f.set_bit(2, c)
            amt.set_value(c, 40)
        (groups,) = ex.execute(
            "hs",
            'GroupBy(Rows(f), aggregate=Sum(field="amt"), '
            "having=Condition(sum > 60))",
        )
        assert [(g.group[0]["rowID"], g.count, g.sum) for g in groups] == [
            (2, 2, 80)
        ]


class TestTimeViews:
    def test_row_time_range(self, env):
        holder, ex = env
        idx = holder.create_index("ev")
        idx.create_field(
            "t", FieldOptions(type="time", time_quantum="YMD")
        )
        ex.execute("ev", "Set(1, t=1, timestamp='2019-01-15T00:00')")
        ex.execute("ev", "Set(2, t=1, timestamp='2019-03-02T00:00')")
        ex.execute("ev", "Set(3, t=1, timestamp='2020-01-01T00:00')")
        (res,) = ex.execute(
            "ev", "Row(t=1, from='2019-01-01T00:00', to='2019-12-31T00:00')"
        )
        assert res.columns().tolist() == [1, 2]
        (res,) = ex.execute(
            "ev", "Row(t=1, from='2019-03-01T00:00', to='2020-06-01T00:00')"
        )
        assert res.columns().tolist() == [2, 3]
        # no time bounds → standard view has all
        (res,) = ex.execute("ev", "Row(t=1)")
        assert res.columns().tolist() == [1, 2, 3]


class TestErrors:
    def test_unknown_index_field(self, env):
        holder, ex = env
        with pytest.raises(PQLError):
            ex.execute("nope", "Row(f=1)")
        holder.create_index("i")
        with pytest.raises(PQLError):
            ex.execute("i", "Row(f=1)")

    def test_negative_column_rejected(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        with pytest.raises(PQLError):
            ex.execute("i", "Set(-5, f=1)")
        with pytest.raises(PQLError):
            ex.execute("i", "Clear(-5, f=1)")

    def test_range_on_set_field(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        with pytest.raises(PQLError):
            ex.execute("i", "Range(f > 3)")

    def test_options_shards(self, env):
        holder, ex = env
        _, data, _ = setup_stars(holder)
        (res,) = ex.execute(
            "repos", "Options(Row(stargazer=1), shards=[0])"
        )
        assert res.columns().tolist() == [c for c in data[1] if c < SHARD_WIDTH]

    def test_includes_column(self, env):
        holder, ex = env
        _, data, _ = setup_stars(holder)
        assert ex.execute(
            "repos", "IncludesColumn(Row(stargazer=1), column=10)"
        ) == [True]
        assert ex.execute(
            "repos", "IncludesColumn(Row(stargazer=1), column=11)"
        ) == [False]


class TestSubmitPipelined:
    def test_submit_count_matches_execute(self, env):
        holder, ex = env
        _, data, langs = setup_stars(holder)
        pql = "Count(Intersect(Row(stargazer=1), Row(language=5)))"
        want = ex.execute("repos", pql)[0]
        (d,) = ex.submit("repos", pql)
        assert d.result() == want
        assert d.result() == want  # idempotent resolve

    def test_submit_pipeline_resolves_in_order(self, env):
        """Enqueue several salted Shift queries without blocking, then
        resolve; each matches its eager counterpart (the bench.py method:
        scalars are runtime args, so one compiled program serves every
        salt)."""
        holder, ex = env
        setup_stars(holder)
        pqls = [
            f"Count(Intersect(Row(stargazer=1), Shift(Row(language=5), n={n})))"
            for n in range(4)
        ]
        defs = [ex.submit("repos", p)[0] for p in pqls]
        want = [ex.execute("repos", p)[0] for p in pqls]
        assert [d.result() for d in defs] == want

    def test_submit_sum_min_max_deferred(self, env):
        holder, ex = env
        idx = holder.create_index("vals")
        f = idx.create_field("n", FieldOptions(type="int", min=0, max=1000))
        for col, v in ((1, 7), (2, 100), (3, 900)):
            f.set_value(col, v)
        for name, want in (("Sum", ValCount(1007, 3)), ("Min", ValCount(7, 1)),
                           ("Max", ValCount(900, 1))):
            (d,) = ex.submit("vals", f'{name}(field="n")')
            assert d.result() == want

    def test_submit_row_defers_readback(self, env, monkeypatch):
        """Pipelined bitmap calls enqueue their program at submit but
        perform the [padded, words] readback only at result()."""
        holder, ex = env
        _, data, _ = setup_stars(holder)
        reads = []
        real_asarray = np.asarray

        def counting_asarray(x, *a, **k):
            import jax

            if isinstance(x, jax.Array):
                reads.append(type(x).__name__)
            return real_asarray(x, *a, **k)

        monkeypatch.setattr(
            "pilosa_tpu.executor.executor.np.asarray", counting_asarray
        )
        (d,) = ex.submit("repos", "Row(stargazer=1)")
        assert reads == []  # no device readback at submit time
        assert d.result().columns().tolist() == data[1]
        assert len(reads) == 1

    def test_operand_memo_reuses_assembly_until_write(self, env):
        """Steady-state repeat queries hit the operand memo; any write
        bumps the residency generation, whose listener clears the memo
        EAGERLY (so evictions actually free HBM), and the next assembly
        picks up the patched leaves."""
        from pilosa_tpu.storage import residency

        holder, ex = env
        setup_stars(holder)
        pql = "Count(Row(stargazer=1))"
        before = ex.execute("repos", pql)[0]
        assert ex.execute("repos", pql)[0] == before
        assert len(ex._operand_memo) >= 1  # warmed
        entry_count = len(ex._operand_memo)
        gen0 = residency.global_row_cache().generation
        ex.execute("repos", pql)
        assert len(ex._operand_memo) == entry_count  # hit, no growth
        assert residency.global_row_cache().generation == gen0
        ex.execute("repos", "Set(424242, stargazer=1)")
        assert residency.global_row_cache().generation > gen0
        assert len(ex._operand_memo) == 0  # listener cleared eagerly
        assert ex.execute("repos", pql)[0] == before + 1

    def test_operand_memo_rejects_stale_generation_entry(self, env):
        """A racing store can insert an entry assembled under an old
        generation AFTER the clear (assembler preempted across a write);
        the per-entry generation tag must keep it from ever being
        served."""
        from pilosa_tpu.storage import residency

        holder, ex = env
        setup_stars(holder)
        pql = "Count(Row(stargazer=1))"
        before = ex.execute("repos", pql)[0]
        ex.execute("repos", pql)  # warm the memo
        (mkey, entry), = [(k, v) for k, v in ex._operand_memo.items()][:1]
        # simulate the race: re-insert the pre-write entry with its OLD
        # generation tag after a write cleared the memo
        ex.execute("repos", "Set(424243, stargazer=1)")
        assert len(ex._operand_memo) == 0
        ex._operand_memo[mkey] = entry
        ex._operand_memo_gen = residency.global_row_cache().generation
        assert ex.execute("repos", pql)[0] == before + 1  # not served stale

    def test_topn_does_not_pollute_operand_memo(self, env):
        """TopN phase 2 builds a per-call _Compiled; memoize=False keeps
        those dead-on-arrival entries out of the memo."""
        holder, ex = env
        idx = holder.create_index("i")
        f = idx.create_field("f", FieldOptions(cache_type="ranked"))
        for row in range(5):
            for col in range(row + 1):
                f.set_bit(row, col)
        ex.execute("i", "TopN(f, n=3)")
        n0 = len(ex._operand_memo)
        for _ in range(5):
            ex.execute("i", "TopN(f, n=3)")
        assert len(ex._operand_memo) == n0  # no per-call growth

    def test_submit_snapshots_leaves_against_later_writes(self, env):
        """A pipelined read captures its leaves at submit time: a write
        landing between submit and the (lazy) flush patches the residency
        cache functionally, so the in-flight query still answers from its
        submit-time snapshot while a post-write submit sees the write."""
        holder, ex = env
        _, data, _ = setup_stars(holder)
        pql = "Count(Row(stargazer=1))"
        before = ex.execute("repos", pql)[0]
        (d_old,) = ex.submit("repos", pql)  # enqueued, not yet flushed
        ex.execute("repos", "Set(999999, stargazer=1)")  # lands pre-flush
        (d_new,) = ex.submit("repos", pql)
        assert d_old.result() == before
        assert d_new.result() == before + 1

    def test_submit_writes_and_host_reads_stay_eager(self, env):
        """Writes and host-only reads must execute AT submit time (an
        already-resolved Deferred) — read-your-writes ordering within a
        submitted stream depends on it."""
        holder, ex = env
        setup_stars(holder)
        (d,) = ex.submit("repos", "Set(999, stargazer=1)")
        assert d._finalize is None  # already resolved
        assert d.result() is True
        # the write is visible to a submit enqueued right after
        (d2,) = ex.submit("repos", "Count(Row(stargazer=1))")
        (rows,) = ex.submit("repos", "Rows(stargazer)")
        assert rows._finalize is None  # host-only read: eager
        assert 999 in set(
            ex.execute("repos", "Row(stargazer=1)")[0].columns().tolist()
        )
        assert d2.result() == ex.execute(
            "repos", "Count(Row(stargazer=1))"
        )[0]

    def test_submit_count_microbatch_coalesces(self, env):
        """Pipelined same-shape Counts dispatch as ONE micro-batched
        program; each Deferred gets its own slice of the [B, 2] packed
        readback. Resolving any Deferred flushes a partial group."""
        holder, ex = env
        _, data, langs = setup_stars(holder)
        pqls = [
            "Count(Row(stargazer=1))",
            "Count(Row(stargazer=2))",
            "Count(Row(stargazer=3))",
            "Count(Row(language=5))",
            "Count(Row(language=6))",
        ]
        want = [ex.execute("repos", p)[0] for p in pqls]
        defs = [ex.submit("repos", p)[0] for p in pqls]
        assert ex._pending  # partial group still pending (5 < batch max)
        got = [d.result() for d in defs]  # first resolve flushes the group
        assert got == want
        assert not ex._pending

    def test_submit_microbatch_flushes_at_max(self, env):
        holder, ex = env
        setup_stars(holder)
        ex.microbatch_max = 2
        defs = [
            ex.submit("repos", f"Count(Row(stargazer={r}))")[0]
            for r in (1, 2, 3)
        ]
        # first two flushed as a pair at max; third still pending
        assert sum(len(g["rows"]) for g in ex._pending.values()) == 1
        assert [d.result() for d in defs] == [4, 3, 2]

    def test_submit_microbatch_caps_group_by_argument_bytes(self, env, monkeypatch):
        """Wide queries (many leaves) cap the micro-batch below
        microbatch_max so the batched program's total argument bytes
        stay under budget — XLA accounts every parameter as distinct
        HBM storage, so a 16-query batch of 4-leaf queries at full
        shard counts would fail to compile."""
        holder, ex = env
        setup_stars(holder)
        # each Count(Intersect(a, b)) carries 2 stacked leaves; size the
        # budget so exactly 2 queries (4 leaves) fit per dispatch
        pql = "Count(Intersect(Row(stargazer=1), Row(language=5)))"
        d0 = ex.submit("repos", pql)[0]
        (group,) = ex._pending.values()
        leaf_bytes = sum(l.nbytes for l in group["rows"][0][0])
        d0.result()  # flush the probe group

        ex.microbatch_arg_budget = 2 * leaf_bytes
        flushes = []
        orig = ex._program_batched

        def counting(structure, rk, lr, ns, nq):
            flushes.append(nq)
            return orig(structure, rk, lr, ns, nq)

        monkeypatch.setattr(ex, "_program_batched", counting)
        want = ex.execute("repos", pql)[0]
        defs = [ex.submit("repos", pql)[0] for _ in range(6)]
        assert [d.result() for d in defs] == [want] * 6
        assert flushes == [2, 2, 2], flushes

    def test_store_rejected_row_leaves_no_phantom_field(self, env):
        """A Store with an invalid row must not implicitly create its
        target field (rejected queries leave no schema side effects)."""
        holder, ex = env
        idx = holder.create_index("i")
        idx.create_field("f")
        ex.execute("i", "Set(1, f=1)")
        with pytest.raises(PQLError):
            ex.execute("i", "Store(Row(f=1), g=-3)")
        assert idx.field("g") is None
        with pytest.raises(PQLError):  # string row: implicit field has no keys
            ex.execute("i", 'Store(Row(f=1), g="name")')
        assert idx.field("g") is None

    def test_topn_sees_write_to_highest_candidate(self, env):
        """Regression: the padded candidate matrix must route writes to
        the REAL slot of the highest candidate id (a pad row duplicating
        it would swallow the patch and serve stale counts)."""
        holder, ex = env
        idx = holder.create_index("r")
        f = idx.create_field("f")
        for row, n_bits in [(1, 5), (2, 9), (5, 7)]:  # 3 rows → pads to 4
            for c in range(n_bits):
                f.set_bit(row, c)
        (pairs,) = ex.execute("r", "TopN(f, n=5)")
        assert dict((p.id, p.count) for p in pairs)[5] == 7
        # write to the HIGHEST candidate id, then re-query
        for c in range(20, 25):
            f.set_bit(5, c)
        (pairs,) = ex.execute("r", "TopN(f, n=5)")
        assert dict((p.id, p.count) for p in pairs)[5] == 12

    def test_topn_matrix_chunking_tiny_budget(self, env, monkeypatch):
        """A matrix byte budget so small every chunk holds one candidate
        must still produce identical TopN results (chunk concat)."""
        import pilosa_tpu.executor.executor as ex_mod

        holder, ex = env
        idx = holder.create_index("r")
        f = idx.create_field("f")
        for row, n_bits in [(1, 5), (2, 9), (3, 7), (4, 3)]:
            for c in range(n_bits):
                f.set_bit(row, c)
        (want,) = ex.execute("r", "TopN(f, n=4)")
        monkeypatch.setattr(ex_mod, "TOPN_MATRIX_BUDGET_BYTES", 1)
        (got,) = ex.execute("r", "TopN(f, n=4)")
        assert [(p.id, p.count) for p in got] == [
            (p.id, p.count) for p in want
        ]
        # pipelined too
        d = ex.submit("r", "TopN(f, n=4)")[0]
        assert [(p.id, p.count) for p in d.result()] == [
            (p.id, p.count) for p in want
        ]

    def test_submit_topn_pipelines_phase2(self, env, monkeypatch):
        """Pipelined TopNs micro-batch their phase-2 recounts: a stream
        of same-field TopNs (same padded candidate shape) dispatches as
        ONE countrows program, with results matching execute()."""
        holder, ex = env
        setup_stars(holder)
        flushes = []
        orig = ex._program_batched

        def counting(structure, rk, lr, ns, nq):
            flushes.append((rk, nq))
            return orig(structure, rk, lr, ns, nq)

        monkeypatch.setattr(ex, "_program_batched", counting)
        want = ex.execute("repos", "TopN(stargazer, n=2)")[0]
        pqls = ["TopN(stargazer, n=2)", "TopN(stargazer, n=3)",
                "TopN(stargazer, n=2)"]
        defs = [ex.submit("repos", p)[0] for p in pqls]
        got = [d.result() for d in defs]
        assert [(p.id, p.count) for p in got[0]] == [
            (p.id, p.count) for p in want
        ]
        assert [(p.id, p.count) for p in got[2]] == [
            (p.id, p.count) for p in want
        ]
        assert len(got[1]) == 3
        # all three phase-2 recounts rode ONE countrows dispatch (the
        # batch axis pads 3 -> 4, the next power of two)
        assert ("countrows", 4) in flushes, flushes
        assert len([f for f in flushes if f[0] == "countrows"]) == 1

    def test_submit_groupby_defers_readback(self, env, monkeypatch):
        """Pipelined dense GroupBys enqueue their level program at
        submit time but perform the host readback only at result():
        submit() must not call np.asarray on the packed result."""
        import pilosa_tpu.executor.executor as ex_mod

        holder, ex = env
        setup_stars(holder)
        want = ex.execute("repos", "GroupBy(Rows(stargazer))")[0]

        unpacks = []
        real_unpack = ex_mod._groupby_level_unpack

        def counting_unpack(*a, **k):
            unpacks.append(1)
            return real_unpack(*a, **k)

        monkeypatch.setattr(ex_mod, "_groupby_level_unpack", counting_unpack)
        d = ex.submit("repos", "GroupBy(Rows(stargazer))")[0]
        assert unpacks == []  # no readback at submit time
        got = d.result()
        assert unpacks == [1]
        assert [g.to_json() for g in got] == [g.to_json() for g in want]

    def test_submit_microbatch_mixed_shapes_group_separately(self, env):
        """Different program shapes (plain vs Shift trees) land in
        different groups and both resolve correctly."""
        holder, ex = env
        setup_stars(holder)
        a = ex.submit("repos", "Count(Row(stargazer=1))")[0]
        b = ex.submit(
            "repos", "Count(Intersect(Row(stargazer=1), Shift(Row(language=5), n=0)))"
        )[0]
        want_b = ex.execute(
            "repos", "Count(Intersect(Row(stargazer=1), Row(language=5)))"
        )[0]
        assert len(ex._pending) == 2
        assert a.result() == 4
        assert b.result() == want_b


class TestPlanCache:
    """_compile_cached: repeated query text (one parse-memoized Call tree)
    reuses the compiled plan; schema changes and BSI shape growth
    invalidate; unknown-key plans are never memoized."""

    def test_repeat_query_hits_cache_and_stays_correct(self, env):
        holder, ex = env
        setup_stars(holder)
        q = "Count(Intersect(Row(stargazer=1), Row(language=5)))"
        assert ex.execute("repos", q)[0] == 3
        assert len(ex._plan_cache) == 1
        entry = next(iter(ex._plan_cache.values()))
        assert ex.execute("repos", q)[0] == 3
        assert next(iter(ex._plan_cache.values())) is entry  # reused

    def test_write_through_cached_plan(self, env):
        holder, ex = env
        setup_stars(holder)
        q = "Count(Row(stargazer=2))"
        assert ex.execute("repos", q)[0] == 3
        holder.index("repos").field("stargazer").set_bit(2, 77)
        assert ex.execute("repos", q)[0] == 4  # plan reused, data fresh

    def test_field_recreate_invalidates(self, env):
        holder, ex = env
        idx = holder.create_index("repos")
        idx.create_field("stargazer").set_bit(1, 5)
        q = "Count(Row(stargazer=1))"
        assert ex.execute("repos", q)[0] == 1
        idx.delete_field("stargazer")
        idx.create_field("stargazer").set_bit(1, 6)
        idx.field("stargazer").set_bit(1, 7)
        assert ex.execute("repos", q)[0] == 2

    def test_bsi_range_recreate_invalidates(self, env):
        """A cached compare plan bakes in base/bit_depth (predicate
        shifting + clamping); recreating the field with a different range
        must not reuse it."""
        holder, ex = env
        idx = holder.create_index("metrics")
        f = idx.create_field("size", FieldOptions(type="int", min=0, max=100))
        f.set_value(1, 50)
        q = "Count(Row(size > 40))"
        assert ex.execute("metrics", q)[0] == 1
        idx.delete_field("size")
        f = idx.create_field(
            "size", FieldOptions(type="int", min=0, max=100000)
        )
        f.set_value(1, 50)
        f.set_value(2, 99999)
        assert ex.execute("metrics", q)[0] == 2

    def test_unknown_key_plan_not_cached(self, env):
        holder, ex = env
        idx = holder.create_index("people", keys=False)
        f = idx.create_field("name", FieldOptions(keys=True))
        ex.execute("people", 'Set(9, name="bob")')  # materialize the field
        q = 'Count(Row(name="alice"))'
        assert ex.execute("people", q)[0] == 0
        assert not ex._plan_cache  # const0 plan: not memoized
        # create the key after the first compile; the same query text
        # (same memoized Call tree) must now see the new row
        ex.execute("people", 'Set(3, name="alice")')
        assert ex.execute("people", q)[0] == 1

    def test_field_delete_shrinks_shard_list(self, env):
        """available_shards memo: a delete_field followed by equal-count
        fragment creation must not alias the memoized shard list."""
        holder, ex = env
        idx = holder.create_index("repos", track_existence=False)
        idx.create_field("a").set_bit(1, 0)  # shard 0
        assert idx.available_shards() == [0]
        idx.delete_field("a")
        idx.create_field("b").set_bit(1, 5 * SHARD_WIDTH)  # shard 5
        assert idx.available_shards() == [5]
        assert ex.execute("repos", "Count(Row(b=1))")[0] == 1

    def test_index_recreate_same_name_invalidates(self, env):
        """delete_index + create_index under one name restarts plan_epoch;
        the cached plan must not survive into the new index."""
        holder, ex = env
        idx = holder.create_index("repos", track_existence=False)
        idx.create_field("f").set_bit(1, 10)
        q = "Count(Row(f=1))"
        assert ex.execute("repos", q)[0] == 1
        holder.delete_index("repos")
        idx2 = holder.create_index("repos", track_existence=False)
        idx2.create_field("f").set_bit(1, 20)
        idx2.field("f").set_bit(1, 21)
        assert ex.execute("repos", q)[0] == 2


class TestSubmitBSIAggregates:
    def setup_vals(self, holder):
        from pilosa_tpu.storage import FieldOptions

        idx = holder.create_index("m", track_existence=False)
        f = idx.create_field("v", FieldOptions(type="int", min=-10, max=500))
        self.values = {0: -10, 1: 0, 5: 42, SHARD_WIDTH + 2: 499}
        for c, v in self.values.items():
            f.set_value(c, v)
        g = idx.create_field("w", FieldOptions(type="int", min=0, max=100))
        for c in (3, 7):
            g.set_value(c, c * 10)
        return idx

    def test_pipelined_sums_coalesce_into_one_dispatch(self, env):
        """Pipelined same-shape Sum queries micro-batch like Counts: one
        device program, per-query slices of the packed readback."""
        holder, ex = env
        self.setup_vals(holder)
        want_v = ex.execute("m", 'Sum(field="v")')[0]
        want_v2 = ex.execute("m", 'Sum(Row(v > 0), field="v")')[0]
        defs = [ex.submit("m", 'Sum(field="v")')[0],
                ex.submit("m", 'Sum(field="v")')[0]]
        assert ex._pending  # grouped, not yet dispatched
        got = [d.result() for d in defs]
        assert got == [want_v, want_v]
        assert not ex._pending
        # filtered Sum (different shape) still correct via submit
        assert ex.submit("m", 'Sum(Row(v > 0), field="v")')[0].result() == want_v2

    def test_pipelined_min_max_via_submit(self, env):
        holder, ex = env
        self.setup_vals(holder)
        for pql in ('Min(field="v")', 'Max(field="v")', 'Min(field="w")'):
            want = ex.execute("m", pql)[0]
            assert ex.submit("m", pql)[0].result() == want

    def test_plan_cache_survives_concurrent_ddl_churn(self, env):
        """Queries racing create/delete of an unrelated field must never
        serve a stale plan or crash; the epoch snapshot taken before
        compile prevents a racing DDL from tagging a stale plan current."""
        import threading

        holder, ex = env
        idx = holder.create_index("repos", track_existence=False)
        f = idx.create_field("f")
        for c in (1, 5, 9):
            f.set_bit(1, c)
        errors = []
        stop = threading.Event()

        def churn():
            try:
                for i in range(60):
                    g = idx.create_field("tmp")
                    g.set_bit(1, 2)
                    idx.delete_field("tmp")
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                stop.set()

        def query():
            try:
                while not stop.is_set():
                    assert ex.execute("repos", "Count(Row(f=1))")[0] == 3
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=churn)] + [
            threading.Thread(target=query) for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[0]
        assert ex.execute("repos", "Count(Row(f=1))")[0] == 3


class TestOptionsShardEdges:
    def test_options_duplicate_shards_count_once(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        f = idx.create_field("f")
        for s in range(3):
            f.set_bit(1, s * SHARD_WIDTH + 1)
        assert ex.execute("i", "Options(Count(Row(f=1)), shards=[2, 2, 2])") == [1]
        assert ex.execute("i", "Options(Count(Row(f=1)), shards=[0, 1, 1])") == [2]

    def test_options_shards_restricts_includes_column(self, env):
        holder, ex = env
        idx = holder.create_index("i")
        f = idx.create_field("f")
        col = 2 * SHARD_WIDTH + 7  # shard 2
        f.set_bit(1, col)
        assert ex.execute(
            "i", f"IncludesColumn(Row(f=1), column={col})"
        ) == [True]
        assert ex.execute(
            "i", f"Options(IncludesColumn(Row(f=1), column={col}), shards=[0])"
        ) == [False]
        assert ex.execute(
            "i", f"Options(IncludesColumn(Row(f=1), column={col}), shards=[2])"
        ) == [True]
