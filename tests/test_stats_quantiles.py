"""Windowed-quantile + histogram edge cases for utils/stats.py
(ISSUE 8 satellite): empty window, single sample, and observations
landing exactly on a histogram bucket boundary."""

import numpy as np

from pilosa_tpu.utils.stats import (
    HISTOGRAM_BUCKETS_S,
    StatsClient,
    _quantile,
)


def _hist_buckets(text: str, family: str) -> dict:
    """le → cumulative count for one family's _bucket lines."""
    out = {}
    for line in text.splitlines():
        if line.startswith(f"{family}_bucket"):
            le = line.split('le="', 1)[1].split('"', 1)[0]
            out[le] = float(line.rsplit(" ", 1)[1])
    return out


def test_quantile_empty_window_is_none():
    s = StatsClient()
    assert s.quantile("nothing", 0.5) is None
    # a counted-but-sampleless series cannot exist through the public
    # API (every timing() adds a sample), but the render must not emit
    # quantile lines for series that were never observed
    text = s.prometheus_text()
    assert "quantile" not in text


def test_quantile_single_sample():
    s = StatsClient()
    s.timing("t", 0.042)
    assert s.quantile("t", 0.5) == 0.042
    assert s.quantile("t", 0.95) == 0.042
    assert s.quantile("t", 0.0) == 0.042
    text = s.prometheus_text()
    assert 'pilosa_tpu_t_seconds{quantile="0.5"} 0.042' in text
    assert "pilosa_tpu_t_seconds_count 1" in text


def test_quantile_observation_single_and_empty():
    s = StatsClient()
    assert s.quantile("obs", 0.95) is None
    s.observe("obs", 7)
    assert s.quantile("obs", 0.5) == 7
    assert s.quantile("obs", 0.95) == 7


def test_quantile_helper_bounds():
    # index clamping: q=1.0 must return the max, q=0.0 the min, and a
    # two-sample window must not index past the end
    assert _quantile([1.0], 1.0) == 1.0
    assert _quantile([1.0, 2.0], 1.0) == 2.0
    assert _quantile([1.0, 2.0], 0.0) == 1.0
    assert _quantile([3.0, 1.0, 2.0], 0.5) == 2.0  # sorts internally


def test_histogram_bucket_boundary_exact():
    """A sample exactly ON a bucket bound counts in THAT bucket
    (Prometheus le semantics: cumulative count of observations <= le)."""
    s = StatsClient()
    bound = HISTOGRAM_BUCKETS_S[0]  # 1 ms
    s.timing("edge", bound)          # exactly on the first bound
    s.timing("edge", np.nextafter(bound, 1.0))  # just above
    text = s.prometheus_text()
    buckets = _hist_buckets(text, "pilosa_tpu_edge_hist_seconds")
    assert buckets[f"{bound:g}"] == 1          # on-edge sample included
    assert buckets[f"{HISTOGRAM_BUCKETS_S[1]:g}"] == 2
    assert buckets["+Inf"] == 2


def test_histogram_sample_above_last_bound():
    """Samples past the last finite bound appear ONLY in +Inf."""
    s = StatsClient()
    last = HISTOGRAM_BUCKETS_S[-1]
    s.timing("big", last)        # exactly on the last bound: counted
    s.timing("big", last * 2)    # beyond every finite bound
    text = s.prometheus_text()
    buckets = _hist_buckets(text, "pilosa_tpu_big_hist_seconds")
    assert buckets[f"{last:g}"] == 1
    assert buckets["+Inf"] == 2
    # cumulative monotonicity across ALL bounds
    ordered = [buckets[f"{b:g}"] for b in HISTOGRAM_BUCKETS_S]
    assert ordered == sorted(ordered)


def test_tag_values_escaped_in_exposition():
    """Tag values reach the registry from client-controlled strings
    (the qos_shed tenant tag is the X-Pilosa-Tenant header) — quotes,
    backslashes, and newlines must be escaped or one request corrupts
    the whole /metrics page."""
    s = StatsClient()
    s.count("qos_shed", 1, {"tenant": 'evil"} 1 back\\slash\nline'})
    text = s.prometheus_text()
    assert ('pilosa_tpu_qos_shed_total'
            '{tenant="evil\\"} 1 back\\\\slash\\nline"} 1') in text
    # the page stays single-line-per-sample (the raw newline is gone)
    assert all(l.startswith(("#", "pilosa_tpu_"))
               for l in text.splitlines() if l)


def test_histogram_every_bound_hit_exactly():
    """One sample exactly on EVERY bound: cumulative counts must step
    by one per bucket (no off-by-one at any edge)."""
    s = StatsClient()
    for b in HISTOGRAM_BUCKETS_S:
        s.timing("all", b)
    text = s.prometheus_text()
    buckets = _hist_buckets(text, "pilosa_tpu_all_hist_seconds")
    for i, b in enumerate(HISTOGRAM_BUCKETS_S):
        assert buckets[f"{b:g}"] == i + 1, f"bucket le={b:g}"
    assert buckets["+Inf"] == len(HISTOGRAM_BUCKETS_S)
