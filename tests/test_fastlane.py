"""Serving fast-lane tests (ISSUE 4): keep-alive connection pooling
lifecycle, pre-serialized responses, pipeline dedupe, and cluster-wide
wave batching. `make serving-smoke` gates on this file: the
connection-count oracle proves keep-alive reuse, and the batch route
must return byte-identical results vs per-query dispatch."""

import json
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.parallel.connpool import ConnectionPool
from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import serve_in_thread
from pilosa_tpu.storage import Holder


@pytest.fixture
def node_api(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    api = API(holder)
    server, port, _ = serve_in_thread(api)
    yield f"http://localhost:{port}", api, server
    server.shutdown()
    server.server_close()
    holder.close()


def _post_query(client, node, pql):
    """Edge query with NO shards/remote params — the dedupe-eligible
    request shape (api.query_raw only keys plain edge reads)."""
    return client._call("POST", f"{node}/index/i/query", pql.encode(),
                        content_type="text/plain")


def _seed(node, api, rows=4, per_row=16):
    client = InternalClient()
    client._call("POST", f"{node}/index/i", b"{}")
    client._call("POST", f"{node}/index/i/field/f", b"{}")
    body = {"rows": [], "columns": []}
    for r in range(1, rows + 1):
        body["rows"] += [r] * per_row
        body["columns"] += [r * 3 + 7 * c for c in range(per_row)]
    client._call("POST", f"{node}/index/i/field/f/import",
                 json.dumps(body).encode())
    return client


# ------------------------------------------------------------ pool lifecycle


class TestConnectionPool:
    def test_reuse_across_requests_connection_oracle(self, node_api):
        """N sequential requests through one client ride ONE server
        connection — the keep-alive oracle."""
        node, api, server = node_api
        client = _seed(node, api)
        base_conns = server.connections_opened
        for _ in range(20):
            out = client.query_node(node, "i", "Count(Row(f=1))",
                                    shards=[0], remote=False)
            assert out == {"results": [16]}
        with server.metrics_lock:
            new_conns = server.connections_opened - base_conns
        assert new_conns == 0  # the seeding connection is still serving
        m = client.pool.metrics()
        assert m["pool_connections_created_total"] == 1
        assert m["pool_connections_reused_total"] >= 20

    def test_chunked_request_body_rejected_411_and_connection_closed(
            self, node_api):
        """Chunked bodies can't be drained by the Content-Length logic;
        the server must 411 and close rather than let chunk framing
        poison the next request on the connection."""
        import http.client as hc

        node, api, server = node_api
        host, port = node.replace("http://", "").split(":")
        conn = hc.HTTPConnection(host, int(port), timeout=10)
        conn.putrequest("POST", "/index/i/query")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        conn.send(b"5\r\nCount\r\n0\r\n\r\n")
        resp = conn.getresponse()
        assert resp.status == 411
        assert "chunked" in json.loads(resp.read())["error"]
        assert resp.will_close
        conn.close()

    def test_keepalive_survives_error_responses_and_unread_bodies(
            self, node_api):
        """Error paths must drain unread bodies: a 404 route with a
        body, then a 400 PQL error, then a good query — all on the same
        pooled connection, with no desync."""
        node, api, server = node_api
        client = _seed(node, api)
        with pytest.raises(ClientError) as e:
            client._call("POST", f"{node}/no/such/route", b"x" * 4096)
        assert e.value.status == 404
        with pytest.raises(ClientError) as e:
            client.query_node(node, "i", "Bogus(", shards=[0], remote=False)
        assert e.value.status == 400
        out = client.query_node(node, "i", "Count(Row(f=2))",
                                shards=[0], remote=False)
        assert out == {"results": [16]}
        assert client.pool.metrics()["pool_connections_created_total"] == 1

    def test_half_closed_idle_socket_detected_and_replaced(self):
        """A server that closes idle keep-alive connections (FIN while
        pooled) must not produce request failures: checkout detects the
        readable/EOF socket, discards it, and reconnects."""
        done = threading.Event()
        response = (b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    b"Content-Length: 2\r\n\r\n{}")
        srv = socket.create_server(("localhost", 0))
        port = srv.getsockname()[1]

        def serve():
            # serve exactly one request per connection, then close the
            # socket WITHOUT Connection: close (the keep-alive lie)
            for _ in range(2):
                conn, _ = srv.accept()
                conn.recv(65536)
                conn.sendall(response)
                conn.close()
            done.set()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        pool = ConnectionPool(timeout=5)
        try:
            assert pool.request("GET", f"http://localhost:{port}/x").data \
                == b"{}"
            time.sleep(0.1)  # let the FIN land on the pooled socket
            assert pool.request("GET", f"http://localhost:{port}/x").data \
                == b"{}"
            assert done.wait(5)
            m = pool.metrics()
            assert m["pool_connections_created_total"] == 2
            assert m["pool_connections_discarded_total"] >= 1
        finally:
            pool.close()
            srv.close()

    def test_stale_reuse_race_retries_on_fresh_connection(self):
        """The keep-alive race: the server closes the pooled connection
        only AFTER our request bytes land (no FIN visible at checkout).
        The pool must retry exactly once on a fresh connection."""
        response = (b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    b"Content-Length: 2\r\n\r\n{}")
        srv = socket.create_server(("localhost", 0))
        port = srv.getsockname()[1]
        accepted = []

        def serve():
            # conn 1: answer request A, then close upon receiving B's
            # bytes (mid-request close -> RemoteDisconnected on reuse);
            # conn 2: answer the retried B
            conn, _ = srv.accept()
            accepted.append(1)
            conn.recv(65536)
            conn.sendall(response)
            conn.recv(65536)  # request B arrives on the reused conn
            conn.close()      # ...and dies without a response
            conn2, _ = srv.accept()
            accepted.append(2)
            conn2.recv(65536)
            conn2.sendall(response)
            conn2.close()

        threading.Thread(target=serve, daemon=True).start()
        pool = ConnectionPool(timeout=5)
        try:
            assert pool.request("GET", f"http://localhost:{port}/a").status \
                == 200
            assert pool.request("GET", f"http://localhost:{port}/b").status \
                == 200
            assert accepted == [1, 2]
            assert pool.metrics()["pool_connections_discarded_total"] >= 1
        finally:
            pool.close()
            srv.close()

    def test_dead_node_fails_fast_and_pools_nothing(self):
        """Connect refused on a fresh connection propagates (no retry
        loop), maps to a node-fault ClientError, and leaves nothing
        pooled for the dead peer."""
        srv = socket.create_server(("localhost", 0))
        port = srv.getsockname()[1]
        srv.close()  # nothing listens here any more
        client = InternalClient(timeout=2)
        with pytest.raises(ClientError) as e:
            client.status(f"http://localhost:{port}")
        assert e.value.status is None and e.value.is_node_fault
        assert client.pool.metrics()["pool_idle_connections"] == 0

    def test_concurrent_requests_use_distinct_connections(self, node_api):
        """Exclusive checkout: two in-flight requests (the shape of a
        hedge leg racing its primary — qos/hedge.py) can never share a
        socket; the second request opens connection #2."""
        node, api, server = node_api
        client = _seed(node, api)
        n = 4
        gate = threading.Event()
        errors = []

        def worker():
            gate.wait(5)
            try:
                # slow-ish request: enough work to overlap the others
                client.query_node(node, "i", "Row(f=1)", shards=[0],
                                  remote=False)
            except Exception as e:  # surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(n)]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(30)
        assert not errors
        m = client.pool.metrics()
        # the seed connection plus however many overlaps actually
        # happened; at least one overlap is effectively guaranteed with
        # 4 simultaneous requests
        assert 2 <= m["pool_connections_created_total"] <= n + 1
        assert m["pool_idle_connections"] == \
            m["pool_connections_created_total"] \
            - m["pool_connections_discarded_total"]

    def test_pool_bound_caps_idle_connections(self, node_api):
        node, api, server = node_api
        client = InternalClient(pool_size=2)
        gate = threading.Event()

        def worker():
            gate.wait(5)
            client.status(node)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(30)
        assert client.pool.metrics()["pool_idle_connections"] <= 2


# ----------------------------------------------------- responses + dedupe


class TestFastLaneResponses:
    def test_pre_serialized_bytes_match_legacy_json(self, node_api):
        """Every hot shape's pre-serialized bytes must parse to exactly
        the dict the legacy result_to_json envelope produced."""
        from pilosa_tpu.executor.result import (
            Pair,
            RowResult,
            ValCount,
            result_to_json,
            results_json_bytes,
        )
        from pilosa_tpu.ops.packing import pack_bits

        row = RowResult({0: pack_bits(np.array([1, 5, 9], np.uint64),
                                      1 << 20)})
        results = [7, True, False, None, ValCount(41, 3),
                   [Pair(2, 8), Pair(3, 5, key="k")], row,
                   ["a", "b"], [1, 2, 3]]
        data = results_json_bytes(results)
        assert json.loads(data) == {
            "results": [result_to_json(r) for r in results]
        }
        # RowResult encoding memoizes on the object (identity-keyed
        # encoded-bytes cache)
        assert row._json_bytes is not None
        again = results_json_bytes(results)
        assert again == data

    def test_identical_wave_dedupe_shares_results(self, node_api):
        """Identical concurrent queries collapse to one submit; every
        client still gets the (byte-identical) correct answer."""
        node, api, server = node_api
        client = _seed(node, api)
        serial = _post_query(client, node, "Count(Row(f=1))")

        # hold the dispatcher inside submit for the first (plug) query
        # so the identical burst piles into the NEXT wave deterministically
        real_executor = api.executor
        plug_seen = threading.Event()

        class SlowFirst:
            def __getattr__(self, name):
                return getattr(real_executor, name)

            def submit(self, index, query, **kwargs):
                if not plug_seen.is_set():
                    plug_seen.set()
                    time.sleep(0.8)
                return real_executor.submit(index, query, **kwargs)

        api.executor = SlowFirst()
        try:
            results = [None] * 9
            errors = []

            def worker(k):
                try:
                    results[k] = _post_query(client, node,
                                             "Count(Row(f=1))")
                except Exception as e:
                    errors.append(e)

            plug = threading.Thread(
                target=worker, args=(0,))
            plug.start()
            assert plug_seen.wait(10)
            time.sleep(0.1)  # burst lands while the dispatcher sleeps
            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(1, 9)]
            for t in threads:
                t.start()
            for t in [plug, *threads]:
                t.join(30)
        finally:
            api.executor = real_executor
        assert not errors
        assert all(r == serial for r in results)
        assert api._pipeline.deduped >= 7

    def test_deduped_error_reaches_every_request(self, node_api):
        """A shared submit that errors must fail EVERY deduped request
        with the same 400, not hang or poison followers."""
        node, api, server = node_api
        client = _seed(node, api)
        outcomes = []
        gate = threading.Event()

        def worker():
            gate.wait(5)
            try:
                _post_query(client, node, "Count(Row(ghost=1))")
                outcomes.append("ok")
            except ClientError as e:
                outcomes.append(e.status)

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(30)
        assert outcomes == [400] * 6


# --------------------------------------------------------- batch route


class TestQueryBatchRoute:
    def test_batch_route_byte_identical_to_per_query(self, node_api):
        """The serving-smoke gate: each item of a batched response must
        be byte-for-byte the response the per-query route produces."""
        node, api, server = node_api
        client = _seed(node, api)
        items = [("i", "Count(Row(f=1))", [0]),
                 ("i", "Row(f=2)", [0]),
                 ("i", "TopN(f, n=2)", [0])]
        raw = client._call(
            "POST", f"{node}/internal/query-batch",
            json.dumps({"queries": [
                {"index": i, "query": q, "shards": s} for i, q, s in items
            ]}).encode(), raw=True)
        solo = [client._call(
            "POST", f"{node}/index/{i}/query?shards=0&remote=true",
            q.encode(), content_type="text/plain", raw=True)
            for i, q, _ in items]
        assert raw == b'{"responses":[' + b",".join(solo) + b"]}"

    def test_batch_items_are_isolated(self, node_api):
        """One bad item (missing index, write call, parse error) answers
        its own error; batchmates still succeed."""
        node, api, server = node_api
        client = _seed(node, api)
        out = client.query_batch(node, [
            ("i", "Count(Row(f=1))", [0]),
            ("nope", "Count(Row(f=1))", [0]),
            ("i", "Set(1, f=1)", [0]),
            ("i", "Bogus(", [0]),
            ("i", "Count(Row(f=3))", [0]),
        ])
        assert out[0] == {"results": [16]}
        assert out[1]["status"] == 404
        assert out[2]["status"] == 400 and "write" in out[2]["error"]
        assert out[3]["status"] == 400
        assert out[4] == {"results": [16]}

    def test_client_remembers_no_batch_peer(self, node_api):
        node, api, server = node_api
        client = _seed(node, api)
        assert client.supports_batch(node)
        # an old-wire peer answers 404 to the route and is remembered
        resp = (b"HTTP/1.1 404 Not Found\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: 22\r\n\r\n"
                b'{"error": "not found"}')
        srv = socket.create_server(("localhost", 0))
        port = srv.getsockname()[1]

        def serve():
            conn, _ = srv.accept()
            conn.recv(65536)
            conn.sendall(resp)
            conn.close()

        threading.Thread(target=serve, daemon=True).start()
        old_peer = f"http://localhost:{port}"
        try:
            with pytest.raises(ClientError) as e:
                client.query_batch(old_peer,
                                   [("i", "Count(Row(f=1))", [0])])
            assert e.value.status == 404
            assert not client.supports_batch(old_peer)
        finally:
            srv.close()


class TestWaveBatcher:
    class FakeClient:
        """Counting client: query_batch answers per item; optionally
        fails whole batches or lacks the route."""

        def __init__(self, fail=None, no_route=False, delay=0.0):
            self.batch_calls = []
            self.solo_calls = []
            self.fail = fail
            self.no_route = no_route
            self.delay = delay
            self._no_batch = set()

        def supports_batch(self, uri):
            return uri not in self._no_batch

        def query_node(self, uri, index, pql, shards, remote=True,
                       **kw):
            self.solo_calls.append((uri, pql, tuple(shards)))
            if self.delay:
                time.sleep(self.delay)
            return {"results": [f"solo:{pql}"]}

        def query_batch(self, uri, items):
            self.batch_calls.append((uri, list(items)))
            if self.no_route:
                self._no_batch.add(uri)
                raise ClientError("no route", status=404)
            if self.fail is not None:
                raise self.fail
            if self.delay:
                time.sleep(self.delay)
            return [{"results": [f"batch:{pql}"]} for _, pql, _ in items]

    class Node:
        def __init__(self, id="n1"):
            self.id = id
            self.uri = f"http://{id}"

    def _batcher(self, client):
        from pilosa_tpu.parallel.wavebatch import RemoteWaveBatcher

        return RemoteWaveBatcher(client)

    def test_group_commit_batches_concurrent_queries(self):
        client = self.FakeClient(delay=0.2)
        batcher = self._batcher(client)
        node = self.Node()
        results = [None] * 9
        gate = threading.Event()

        def worker(k):
            if k > 0:
                gate.wait(5)
            results[k] = batcher.query(node, "i", f"Count(Row(f={k}))",
                                       [k])

        leader = threading.Thread(target=worker, args=(0,))
        leader.start()
        time.sleep(0.05)  # leader's flush is in flight (solo, delayed)
        gate.set()
        rest = [threading.Thread(target=worker, args=(k,))
                for k in range(1, 9)]
        for t in rest:
            t.start()
        for t in [leader, *rest]:
            t.join(30)
        # the stragglers arriving during the leader's round trip must
        # have shipped as (at most a couple of) multi-query batches
        assert results[0] == {"results": ["solo:Count(Row(f=0))"]}
        for k in range(1, 9):
            assert results[k] == {"results": [f"batch:Count(Row(f={k}))"]}
        assert client.batch_calls  # a real batch formed
        assert batcher.metrics()["remote_batched_queries_total"] == 8

    def test_batch_transport_failure_fails_each_member_like_direct(self):
        """The leader's solo flush succeeds; two stragglers batch while
        it is in flight, the batch transport fails, and EACH straggler
        gets its own node-fault ClientError (replica fallback shape)."""
        client = self.FakeClient(fail=ClientError("boom"))
        batcher = self._batcher(client)
        node = self.Node()
        errors = {}
        gate = threading.Event()
        release = threading.Event()
        orig_solo = client.query_node

        def gated_solo(uri, index, pql, shards, remote=True, **kw):
            gate.set()
            release.wait(5)
            return orig_solo(uri, index, pql, shards, remote=remote, **kw)

        client.query_node = gated_solo

        def worker(k):
            try:
                batcher.query(node, "i", f"Q{k}", [k])
            except ClientError as e:
                errors[k] = e

        t0 = threading.Thread(target=worker, args=(0,))
        t0.start()
        assert gate.wait(5)  # leader's solo flush in flight
        t1 = threading.Thread(target=worker, args=(1,))
        t2 = threading.Thread(target=worker, args=(2,))
        t1.start()
        t2.start()
        time.sleep(0.1)
        release.set()
        for t in (t0, t1, t2):
            t.join(10)
        assert 0 not in errors  # the solo leader succeeded
        assert set(errors) == {1, 2}
        assert all(e.is_node_fault for e in errors.values())
        assert errors[1] is not errors[2]  # per-caller exception objects

    def test_malformed_batch_item_fails_only_its_slot_and_lane_survives(self):
        """A peer answering 200 with a malformed item (null) must fail
        THAT slot with a ClientError; well-formed batchmates resolve,
        nothing hangs, and the node's lane keeps working afterwards."""
        client = self.FakeClient()
        real_batch = client.query_batch

        def mangled(uri, items):
            out = real_batch(uri, items)
            out[0] = None  # malformed first item
            return out

        client.query_batch = mangled
        batcher = self._batcher(client)
        node = self.Node()
        gate = threading.Event()
        release = threading.Event()
        orig_solo = client.query_node

        def gated_solo(uri, index, pql, shards, remote=True, **kw):
            gate.set()
            release.wait(5)
            return orig_solo(uri, index, pql, shards, remote=remote, **kw)

        client.query_node = gated_solo
        outcomes = {}

        def worker(k):
            try:
                outcomes[k] = batcher.query(node, "i", f"Q{k}", [k])
            except ClientError as e:
                outcomes[k] = ("err", str(e))

        t0 = threading.Thread(target=worker, args=(0,))
        t0.start()
        assert gate.wait(5)
        t1 = threading.Thread(target=worker, args=(1,))
        t2 = threading.Thread(target=worker, args=(2,))
        t1.start()
        t2.start()
        time.sleep(0.1)
        release.set()
        for t in (t0, t1, t2):
            t.join(10)
        assert outcomes[0] == {"results": ["solo:Q0"]}
        assert outcomes[1][0] == "err" and "malformed" in outcomes[1][1]
        assert outcomes[2] == {"results": ["batch:Q2"]}
        # the lane is NOT wedged: a fresh query flushes normally
        client.query_node = orig_solo
        client.query_batch = real_batch
        assert batcher.query(node, "i", "Q9", [9]) == \
            {"results": ["solo:Q9"]}

    def test_no_route_peer_replays_individually_then_goes_direct(self):
        client = self.FakeClient(no_route=True, delay=0)
        batcher = self._batcher(client)
        node = self.Node()
        gate = threading.Event()
        release = threading.Event()
        orig_solo = client.query_node

        def gated_solo(uri, index, pql, shards, remote=True, **kw):
            if pql == "Q0":
                gate.set()
                release.wait(5)
            return orig_solo(uri, index, pql, shards, remote=remote, **kw)

        client.query_node = gated_solo
        results = {}

        def worker(k):
            results[k] = batcher.query(node, "i", f"Q{k}", [k])

        t0 = threading.Thread(target=worker, args=(0,))
        t0.start()
        assert gate.wait(5)
        t1 = threading.Thread(target=worker, args=(1,))
        t2 = threading.Thread(target=worker, args=(2,))
        t1.start()
        t2.start()
        time.sleep(0.1)
        release.set()
        for t in (t0, t1, t2):
            t.join(10)
        # first flush was solo (leader); the follow-up batch hit the 404
        # and replayed per-query; afterwards the peer is known no-batch
        assert results == {k: {"results": [f"solo:Q{k}"]} for k in range(3)}
        assert len(client.batch_calls) == 1
        assert batcher.metrics()["remote_batch_fallbacks_total"] >= 2


# ------------------------------------------------- cluster sync fast path


class TestEmptyFragmentProbe:
    def test_fetch_skips_payload_when_all_replicas_empty(self, tmp_path):
        """ADVICE r4 #4: a legitimately-empty fragment is probed via the
        cheap block-checksum list, never re-fetched as a full payload."""
        from pilosa_tpu.parallel.cluster import Cluster, Node

        holder = Holder(str(tmp_path / "d")).open()
        holder.create_index("i").create_field("f")

        calls = {"blocks": 0, "data": 0}

        class FakeClient:
            def fragment_blocks(self, uri, index, field, view, shard):
                calls["blocks"] += 1
                return []  # empty on every replica

            def fragment_data(self, uri, index, field, view, shard):
                calls["data"] += 1
                return b""

        cluster = Cluster(Node("n0", "http://n0"), holder=holder)
        cluster.client = FakeClient()
        fetched = cluster.fetch_fragments([
            {"index": "i", "field": "f", "view": "standard", "shard": 0,
             "from": "http://n1", "fallbacks": ["http://n2"]},
        ])
        assert fetched == 0
        assert calls["blocks"] == 2  # probed both replicas
        assert calls["data"] == 0    # no full payload was transferred
        holder.close()

    def test_fetch_still_pulls_data_after_nonempty_probe(self, tmp_path):
        from pilosa_tpu.parallel.cluster import Cluster, Node
        from pilosa_tpu.roaring import RoaringBitmap
        from pilosa_tpu.roaring.format import serialize

        holder = Holder(str(tmp_path / "d")).open()
        holder.create_index("i").create_field("f")
        payload = serialize(RoaringBitmap.from_ids([1, 5, (1 << 20) - 1]))

        class FakeClient:
            def fragment_blocks(self, uri, index, field, view, shard):
                return [(0, "abc")]

            def fragment_data(self, uri, index, field, view, shard):
                return payload

        cluster = Cluster(Node("n0", "http://n0"), holder=holder)
        cluster.client = FakeClient()
        fetched = cluster.fetch_fragments([
            {"index": "i", "field": "f", "view": "standard", "shard": 0,
             "from": "http://n1"},
        ])
        assert fetched == 1
        frag = holder.index("i").field("f").view("standard").fragment(0)
        assert frag.count() == 3
        holder.close()


# --------------------------------------------------------------- config


def test_fastlane_config_knobs_round_trip():
    from pilosa_tpu.server.server import ServerConfig

    cfg = ServerConfig(client_pool_size=3, remote_batch=False)
    d = cfg.to_dict()
    assert d["client-pool-size"] == 3 and d["remote-batch"] is False
    back = ServerConfig.from_dict(d)
    assert back.client_pool_size == 3 and back.remote_batch is False
    # env-var style strings parse too
    assert ServerConfig.from_dict({"remote-batch": "false"}).remote_batch \
        is False


def test_generate_config_documents_fastlane_knobs(capsys):
    from pilosa_tpu import cli

    cli.main(["generate-config"])
    out = capsys.readouterr().out
    assert "client-pool-size" in out and "remote-batch" in out


def test_metrics_export_serving_fastlane_series(node_api):
    node, api, server = node_api
    text = urllib.request.urlopen(f"{node}/metrics").read().decode()
    for series in ("serving_pool_connections_created_total",
                   "serving_remote_batches_total",
                   "serving_deduped_requests_total",
                   "serving_http_connections_total",
                   "serving_http_requests_total"):
        assert f"pilosa_tpu_{series}" in text, series
    dv = json.loads(
        urllib.request.urlopen(f"{node}/debug/vars").read())
    assert "remote_batches_total" in dv["serving_fastlane"]
    assert dv["serving_fastlane"]["http_connections_total"] >= 1
