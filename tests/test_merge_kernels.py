"""Property tests: whole-batch merge kernels vs the retired
per-container write loop (pilosa_tpu/roaring/merge_kernels.py).

The kernels' contract is BYTE-IDENTITY with ``_merge_loop`` — the
per-container merge kept verbatim in bitmap.py as the small-batch path
and THE reference here. Every test serializes both results and
compares bytes, over randomized array/bitmap/run mixes, adversarial
batches (promote-threshold boundaries, container-filling adds,
remove-to-empty), the mutex/BSI merge rules, the batched membership
probes, and WAL-replay equivalence (the crash ledger replays through
the same dispatcher, so both paths must reconstruct identical bytes).
"""

import numpy as np
import pytest

from pilosa_tpu.roaring import merge_kernels, serialize
from pilosa_tpu.roaring.bitmap import (
    ARRAY_MAX,
    BITMAP,
    RUN,
    RoaringBitmap,
)
from pilosa_tpu.roaring.format import OP_ADD, OP_REMOVE
from pilosa_tpu.storage.fragment import Fragment

from tests.test_roaring_kernels import make_bitmap

U = np.uint64


def make_pair(rng, n_containers, kinds="mixed", key_span=64):
    """Two byte-identical bitmaps: one merges via the kernel, one via
    the reference loop."""
    bm = make_bitmap(rng, n_containers, kinds=kinds, key_span=key_span)
    ref = deser_clone(bm)
    return bm, ref


def deser_clone(bm):
    from pilosa_tpu.roaring.format import deserialize

    clone, _ = deserialize(serialize(bm))
    return clone


def assert_merge_identical(bm, ref, batch, remove):
    got = merge_kernels.merge_ids(bm, batch.copy(), remove)
    want = ref._merge_loop(batch.copy(), remove)
    assert got == want, (got, want, remove)
    assert serialize(bm) == serialize(ref)
    assert bm.keys == ref.keys


# ------------------------------------------------------- randomized fuzz


@pytest.mark.parametrize("seed", range(10))
def test_merge_matches_loop_randomized(seed):
    rng = np.random.default_rng(seed)
    for _ in range(6):
        bm, ref = make_pair(rng, int(rng.integers(0, 30)))
        span = int(rng.integers(1, 64)) << 16
        batch = rng.integers(0, span,
                             int(rng.integers(64, 20000))).astype(U)
        assert_merge_identical(bm, ref, batch, bool(rng.integers(0, 2)))


@pytest.mark.parametrize("kind", ["array", "bitmap", "run", "full",
                                  "single"])
def test_merge_matches_loop_each_kind(kind):
    rng = np.random.default_rng(hash(kind) % 2**32)
    for remove in (False, True):
        bm, ref = make_pair(rng, 8, kinds=kind, key_span=8)
        batch = rng.integers(0, 8 << 16, 5000).astype(U)
        assert_merge_identical(bm, ref, batch, remove)


def test_merge_duplicate_and_unsorted_batches():
    rng = np.random.default_rng(3)
    bm, ref = make_pair(rng, 10)
    base = rng.integers(0, 16 << 16, 4000).astype(U)
    batch = np.concatenate([base, base[:1000], base[::-1]])
    assert_merge_identical(bm, ref, batch, False)


# ------------------------------------------------------ adversarial edges


def test_array_promote_threshold_boundary():
    # the reference promotes an ARRAY to word space when
    # c.n + deduped-batch-size crosses ARRAY_MAX — probe the exact
    # boundary from both sides
    for base_n in (ARRAY_MAX - 10, ARRAY_MAX - 1, ARRAY_MAX):
        for extra in (9, 10, 11, 12):
            pre = np.arange(base_n, dtype=U) * U(3)
            bm = RoaringBitmap.from_ids(pre)
            ref = RoaringBitmap.from_ids(pre)
            batch = np.arange(extra, dtype=U) * U(3) + U(1)
            assert_merge_identical(bm, ref, batch, False)


def test_bitmap_stays_bitmap_above_array_max():
    # non-canonical on purpose: a merged bitmap container above
    # ARRAY_MAX keeps BITMAP kind even where runs would be smaller
    rng = np.random.default_rng(0)
    pre = np.unique(rng.integers(0, 65536, 60000)).astype(U)
    bm = RoaringBitmap.from_ids(pre)
    ref = RoaringBitmap.from_ids(pre)
    assert bm._containers[0].kind == BITMAP
    assert_merge_identical(bm, ref, np.arange(65536, dtype=U), False)
    assert bm._containers[0].kind == BITMAP
    assert ref._containers[0].kind == BITMAP


def test_delta_zero_keeps_existing_container_object():
    # a no-op merge must not rebuild the container (the loop keeps the
    # object; readers hold references)
    pre = np.arange(0, 130000, 2, dtype=U)
    bm = RoaringBitmap.from_ids(pre)
    before = dict(bm._containers)
    batch = np.arange(0, 130000, 4, dtype=U)  # all already set
    assert merge_kernels.merge_ids(bm, batch, False) == 0
    for key, c in before.items():
        assert bm._containers[key] is c


def test_remove_to_empty_pops_containers():
    pre = np.arange(200, dtype=U) + (U(5) << U(16))
    bm = RoaringBitmap.from_ids(pre)
    ref = RoaringBitmap.from_ids(pre)
    batch = np.concatenate([pre, np.arange(64, dtype=U)])  # key 0 absent
    assert_merge_identical(bm, ref, batch, True)
    assert bm.keys == []


def test_run_existing_containers_merge():
    # run containers take the sorted-stream path: their payloads expand
    # in one vectorized pass and the rebuilt kind re-derives from the
    # from_lows cost model
    pre = np.arange(60000, dtype=U)
    bm = RoaringBitmap.from_ids(pre)
    ref = RoaringBitmap.from_ids(pre)
    assert bm._containers[0].kind == RUN
    assert_merge_identical(
        bm, ref, np.arange(60000, 65536, dtype=U), False)


def test_small_batches_fall_back_to_loop():
    stats = merge_kernels.global_merge_stats()
    before = stats.loop_fallbacks
    bm = RoaringBitmap()
    bm.add_ids(np.arange(merge_kernels.KERNEL_MIN_IDS - 1, dtype=U))
    assert stats.loop_fallbacks == before + 1
    ref = RoaringBitmap()
    ref._merge_loop(np.arange(merge_kernels.KERNEL_MIN_IDS - 1,
                              dtype=U), False)
    assert serialize(bm) == serialize(ref)


# ----------------------------------------------------- membership probes


@pytest.mark.parametrize("seed", range(4))
def test_set_rows_for_positions_matches_row_member(seed):
    rng = np.random.default_rng(seed)
    ids = ((rng.integers(0, 30, 20000).astype(U) << U(20))
           + rng.integers(0, 1 << 20, 20000).astype(U))
    bm = RoaringBitmap.from_ids(ids)
    pos = rng.integers(0, 1 << 20, 3000).astype(U)
    rows_k, idx_k = merge_kernels.set_rows_for_positions(bm, pos)
    got = {(int(r), int(i)) for r, i in zip(rows_k, idx_k)}
    want = set()
    for r in sorted({k >> 4 for k in bm.keys}):
        m = bm.row_member(r, pos)
        want.update((int(r), int(i)) for i in np.nonzero(m)[0])
    assert got == want


@pytest.mark.parametrize("seed", range(4))
def test_member_matrix_matches_row_member(seed):
    rng = np.random.default_rng(100 + seed)
    ids = ((rng.integers(0, 40, 15000).astype(U) << U(20))
           + rng.integers(0, 1 << 20, 15000).astype(U))
    bm = RoaringBitmap.from_ids(ids)
    pos = rng.integers(0, 1 << 20, 2000).astype(U)
    rows = [0, 2, 3, 7, 39, 41]  # row 41 has no containers
    got = merge_kernels.member_matrix(bm, rows, pos)
    for i, r in enumerate(rows):
        assert np.array_equal(got[i], bm.row_member(r, pos)), r


# ------------------------------------------------- mutex/BSI merge rules


def _frag(tmp_path, name, field_kind="set"):
    return Fragment(str(tmp_path / name), "i", field_kind,
                    "standard", 0).open()


def _frag_pairs(frag):
    ids = frag.bitmap.to_ids()
    return {(int(i) >> 20, int(i) & ((1 << 20) - 1)) for i in ids}


@pytest.mark.parametrize("seed", range(4))
def test_import_mutex_matches_sequential_semantics(seed, tmp_path):
    # the mutex rule, stated independently: each column keeps exactly
    # its LAST imported row; previously-set other rows clear; changed
    # counts columns whose bit was newly added
    rng = np.random.default_rng(seed)
    frag = _frag(tmp_path, f"m{seed}")
    n0 = int(rng.integers(0, 4000))
    r0 = rng.integers(0, 16, n0).astype(U)
    p0 = rng.integers(0, 1 << 20, n0).astype(U)
    frag.import_mutex(r0.copy(), p0.copy())

    model = {}  # column -> row (sequential set-with-clear semantics)
    for r, p in zip(r0.tolist(), p0.tolist()):
        model[p] = r

    n1 = int(rng.integers(1, 4000))
    r1 = rng.integers(0, 16, n1).astype(U)
    p1 = rng.integers(0, 1 << 20, n1).astype(U)
    changed = frag.import_mutex(r1.copy(), p1.copy())

    want_changed = 0
    final = dict(model)
    for p, r in {int(p): int(r) for p, r in zip(p1, r1)}.items():
        if final.get(p) != r:
            want_changed += 1
        final[p] = r
    assert changed == want_changed
    assert _frag_pairs(frag) == {(r, p) for p, r in final.items()}
    frag.close()


@pytest.mark.parametrize("seed", range(3))
def test_add_ids_mutex_keeps_local_rows(seed, tmp_path):
    rng = np.random.default_rng(10 + seed)
    frag = _frag(tmp_path, f"am{seed}")
    n0 = int(rng.integers(1, 3000))
    r0 = rng.integers(0, 12, n0).astype(U)
    p0 = rng.integers(0, 1 << 20, n0).astype(U)
    frag.import_mutex(r0.copy(), p0.copy())
    local = {p: r for r, p in _frag_pairs(frag)}
    local_pairs = _frag_pairs(frag)

    n1 = int(rng.integers(1, 3000))
    incoming = ((rng.integers(0, 12, n1).astype(U) << U(20))
                + rng.integers(0, 1 << 20, n1).astype(U))
    frag.add_ids_mutex(incoming.copy())

    # survivors: keep-last per incoming column, dropped when the local
    # fragment holds the column in a DIFFERENT row
    cand = {}
    for i in incoming.tolist():
        cand[i & ((1 << 20) - 1)] = i >> 20
    want = set(local_pairs)
    for p, r in cand.items():
        if p in local and local[p] != r:
            continue
        want.add((r, p))
    assert _frag_pairs(frag) == want
    frag.close()


@pytest.mark.parametrize("seed", range(4))
def test_import_bsi_matches_value_semantics(seed, tmp_path):
    rng = np.random.default_rng(20 + seed)
    frag = _frag(tmp_path, f"b{seed}")
    depth = int(rng.integers(1, 33))
    model = {}  # column -> stored value
    for _ in range(3):
        pos = np.unique(
            rng.integers(0, 1 << 20, int(rng.integers(1, 2500)))
        ).astype(U)
        vals = rng.integers(0, 1 << depth, pos.size).astype(U)
        changed = frag.import_bsi(pos.copy(), vals.copy(), depth)
        want_changed = 0
        for p, v in zip(pos.tolist(), vals.tolist()):
            if model.get(p) != v:
                want_changed += 1
            model[p] = v
        assert changed == want_changed
        want = set()
        for p, v in model.items():
            want.add((0, p))  # exists row
            for i in range(depth):
                if (v >> i) & 1:
                    want.add((2 + i, p))
        assert _frag_pairs(frag) == want
    frag.close()


# --------------------------------------------------- WAL-replay identity


@pytest.mark.parametrize("seed", range(3))
def test_replay_identical_through_kernel_and_loop(seed, tmp_path,
                                                  monkeypatch):
    # the crash ledger replays through the same dispatcher as live
    # writes — a recovered fragment must be bit-exact no matter which
    # path (kernel or loop) applied each op
    rng = np.random.default_rng(30 + seed)
    ops = []
    for _ in range(8):
        n = int(rng.integers(1, 6000))
        ids = ((rng.integers(0, 24, n).astype(U) << U(20))
               + rng.integers(0, 1 << 20, n).astype(U))
        ops.append((OP_ADD if rng.integers(0, 3) else OP_REMOVE, ids))

    frag_k = _frag(tmp_path, "rk")
    for op, ids in ops:
        frag_k.apply_recovered(op, ids.copy())
    kernel_bytes = serialize(frag_k.bitmap)
    frag_k.close()

    # force every merge through the retired loop
    monkeypatch.setattr(merge_kernels, "KERNEL_MIN_IDS", 1 << 62)
    frag_l = _frag(tmp_path, "rl")
    for op, ids in ops:
        frag_l.apply_recovered(op, ids.copy())
    assert serialize(frag_l.bitmap) == kernel_bytes
    frag_l.close()


def test_merge_stats_counters_advance():
    stats = merge_kernels.global_merge_stats()
    calls, ids_n = stats.kernel_calls, stats.ids_merged
    bm = RoaringBitmap()
    batch = np.arange(5000, dtype=U)
    merge_kernels.merge_ids(bm, batch, False)
    assert stats.kernel_calls == calls + 1
    assert stats.ids_merged == ids_n + 5000
    for key in ("ingest_merge_kernel_calls_total",
                "ingest_merge_ids_total",
                "ingest_merge_loop_fallbacks_total",
                "ingest_merge_probe_calls_total"):
        assert key in stats.metrics()
