"""Protobuf wire-format tests: content negotiation on /query and imports
(reference encoding/proto + handler negotiation — SURVEY.md §2 #16)."""

import urllib.request

import numpy as np
import pytest

from pilosa_tpu import wire
from tests.test_http import node, node_api, req  # fixture reuse

requires_proto = pytest.mark.skipif(
    not wire.available(), reason="protoc/protobuf runtime unavailable"
)


def praw(method, url, body=None, content_type=None, accept=None):
    r = urllib.request.Request(url, data=body, method=method)
    if content_type:
        r.add_header("Content-Type", content_type)
    if accept:
        r.add_header("Accept", accept)
    with urllib.request.urlopen(r) as resp:
        return resp.read(), resp.headers.get("Content-Type")


@requires_proto
def test_query_protobuf_roundtrip(node):
    from pilosa_tpu.wire import pb2
    from pilosa_tpu.wire.serializer import (
        RESULT_CHANGED, RESULT_COUNT, RESULT_PAIRS, RESULT_ROW, RESULT_VALCOUNT,
    )

    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/field/v",
        {"options": {"type": "int", "min": 0, "max": 100}})

    p = pb2()
    # protobuf request body + protobuf response
    qr = p.QueryRequest(query="Set(3, f=1) Set(5, f=1)")
    raw, ct = praw(
        "POST", f"{node}/index/i/query", qr.SerializeToString(),
        content_type="application/x-protobuf", accept="application/x-protobuf",
    )
    assert ct == "application/x-protobuf"
    resp = p.QueryResponse(); resp.ParseFromString(raw)
    assert [r.type for r in resp.results] == [RESULT_CHANGED] * 2
    assert all(r.changed for r in resp.results)

    req("POST", f"{node}/index/i/field/v/import-value",
        {"columns": [3, 5], "values": [10, 20]})

    qr = p.QueryRequest(
        query='Row(f=1) Count(Row(f=1)) TopN(f, n=1) Sum(field="v")'
    )
    raw, _ = praw(
        "POST", f"{node}/index/i/query", qr.SerializeToString(),
        content_type="application/x-protobuf", accept="application/x-protobuf",
    )
    resp = p.QueryResponse(); resp.ParseFromString(raw)
    row, count, topn, vc = resp.results
    assert row.type == RESULT_ROW and list(row.row.columns) == [3, 5]
    assert count.type == RESULT_COUNT and count.n == 2
    assert topn.type == RESULT_PAIRS and topn.pairs[0].count == 2
    assert vc.type == RESULT_VALCOUNT and (vc.val_count.value, vc.val_count.count) == (30, 2)


@requires_proto
def test_protobuf_request_json_response(node):
    from pilosa_tpu.wire import pb2

    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    p = pb2()
    qr = p.QueryRequest(query="Count(Row(f=1))")
    raw, ct = praw(
        "POST", f"{node}/index/i/query", qr.SerializeToString(),
        content_type="application/x-protobuf",
    )
    assert ct == "application/json"
    import json

    assert json.loads(raw) == {"results": [0]}


@requires_proto
def test_protobuf_import(node):
    from pilosa_tpu.wire import pb2

    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    p = pb2()
    imp = p.ImportRequest(row_ids=[1, 1, 2], column_ids=[10, 11, 10])
    out, _ = praw(
        "POST", f"{node}/index/i/field/f/import", imp.SerializeToString(),
        content_type="application/x-protobuf",
    )
    import json

    assert json.loads(out)["changed"] == 3
    assert req("POST", f"{node}/index/i/query", b"Count(Row(f=1))")["results"] == [2]

    vimp = p.ImportValueRequest(column_ids=[7], values=[42])
    req("POST", f"{node}/index/i/field/vv",
        {"options": {"type": "int", "min": 0, "max": 100}})
    out, _ = praw(
        "POST", f"{node}/index/i/field/vv/import-value", vimp.SerializeToString(),
        content_type="application/x-protobuf",
    )
    assert json.loads(out)["changed"] == 1


@requires_proto
def test_protobuf_error_response(node):
    from pilosa_tpu.wire import pb2

    req("POST", f"{node}/index/i", {})
    p = pb2()
    qr = p.QueryRequest(query="Row(missing=1)")
    r = urllib.request.Request(
        f"{node}/index/i/query", data=qr.SerializeToString(), method="POST")
    r.add_header("Content-Type", "application/x-protobuf")
    r.add_header("Accept", "application/x-protobuf")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(r)
    resp = p.QueryResponse(); resp.ParseFromString(e.value.read())
    assert "missing" in resp.err


@requires_proto
def test_groupby_and_keys_over_protobuf(node):
    from pilosa_tpu.wire import pb2
    from pilosa_tpu.wire.serializer import RESULT_GROUPS, RESULT_ROW

    req("POST", f"{node}/index/k", {"options": {"keys": True}})
    req("POST", f"{node}/index/k/field/likes", {"options": {"keys": True}})
    req("POST", f"{node}/index/k/query", b'Set("a", likes="x") Set("b", likes="x")')
    p = pb2()
    qr = p.QueryRequest(query='Row(likes="x")')
    raw, _ = praw("POST", f"{node}/index/k/query", qr.SerializeToString(),
                  content_type="application/x-protobuf",
                  accept="application/x-protobuf")
    resp = p.QueryResponse(); resp.ParseFromString(raw)
    assert resp.results[0].type == RESULT_ROW
    assert sorted(resp.results[0].row.keys) == ["a", "b"]


@requires_proto
def test_keyed_groupby_over_protobuf(node):
    from pilosa_tpu.wire import pb2
    from pilosa_tpu.wire.serializer import RESULT_GROUPS

    req("POST", f"{node}/index/g", {})
    req("POST", f"{node}/index/g/field/lang", {"options": {"keys": True}})
    req("POST", f"{node}/index/g/query",
        b'Set(1, lang="go") Set(2, lang="go") Set(2, lang="py")')
    p = pb2()
    qr = p.QueryRequest(query="GroupBy(Rows(lang))")
    raw, _ = praw("POST", f"{node}/index/g/query", qr.SerializeToString(),
                  content_type="application/x-protobuf",
                  accept="application/x-protobuf")
    resp = p.QueryResponse(); resp.ParseFromString(raw)
    assert resp.results[0].type == RESULT_GROUPS
    got = {g.group[0].row_key: g.count for g in resp.results[0].groups}
    assert got == {"go": 2, "py": 1}
    assert all(g.group[0].field == "lang" for g in resp.results[0].groups)


@requires_proto
def test_import_request_encoders_roundtrip():
    """Client-side request encoders invert the server-side decoders — the
    routed-import protobuf hop (parallel/client.py import_bits/values)."""
    from pilosa_tpu.wire.serializer import (
        decode_import_request,
        decode_import_value_request,
        encode_import_request,
        encode_import_value_request,
    )

    body = encode_import_request(
        "i", "f", [1, 2, 3], [10, 20, 1 << 40],
        timestamps=["2019-01-15T00:00", None, ""], clear=True,
    )
    rows, cols, ts, clear = decode_import_request(body)
    # decoders return numpy (the import path consumes arrays directly)
    assert rows.dtype == np.uint64 and rows.tolist() == [1, 2, 3]
    assert cols.tolist() == [10, 20, 1 << 40]
    assert ts == ["2019-01-15T00:00", "", ""]  # None -> "" (= no timestamp)
    assert clear is True

    body = encode_import_value_request("i", "v", [5, 6], [-7, 1 << 40],
                                       clear=False)
    cols, values, clear = decode_import_value_request(body)
    assert cols.tolist() == [5, 6]
    assert values.dtype == np.int64 and values.tolist() == [-7, 1 << 40]
    assert clear is False


@requires_proto
def test_decode_results_json_matches_json_shapes():
    """decode_results_json (the remote-partial decoder) emits exactly the
    shapes executor/result.py to_json emits, for every result type the
    cluster reducer consumes."""
    import numpy as np

    from pilosa_tpu.executor.result import (
        GroupCount,
        Pair,
        RowResult,
        ValCount,
        result_to_json,
    )
    from pilosa_tpu.ops.packing import pack_bits
    from pilosa_tpu.wire.serializer import decode_results_json, encode_results

    row = RowResult({0: np.asarray(pack_bits(np.asarray([3, 17]), 1 << 20))})
    keyed = RowResult({}, keys=["alice", "bob"])
    results = [
        row, keyed, 42, True, None, ValCount(-5, 3),
        [Pair(1, 9), Pair(2, 4, key="k")],
        [GroupCount([{"field": "a", "rowID": 1},
                     {"field": "b", "rowKey": "x"}], 7, sum=-2)],
        [10, 20], ["r1", "r2"],
    ]
    got = decode_results_json(encode_results(results))["results"]
    want = [result_to_json(r) for r in results]
    # RowResult JSON carries attrs; the reducer reads columns/keys
    assert got[0]["columns"] == want[0]["columns"]
    assert got[1]["keys"] == want[1]["keys"]
    for g, w in zip(got[2:], want[2:]):
        assert g == w, (g, w)


@requires_proto
def test_column_attrs_survive_protobuf():
    """columnAttrs option output rides the wire (QueryResult.column_attrs)
    and decodes back to the JSON surface's columnAttrs shape."""
    import numpy as np

    from pilosa_tpu.executor.result import RowResult, result_to_json
    from pilosa_tpu.ops.packing import pack_bits
    from pilosa_tpu.wire.serializer import decode_results_json, encode_results

    row = RowResult({0: np.asarray(pack_bits(np.asarray([1, 2]), 1 << 20))})
    row.column_attrs = [
        {"id": 1, "attrs": {"city": "nyc", "n": 3, "vip": True}},
    ]
    (got,) = decode_results_json(encode_results([row]))["results"]
    assert got["columnAttrs"] == result_to_json(row)["columnAttrs"]


@requires_proto
def test_protobuf_request_carries_result_options(node):
    """Protobuf clients set request-level result options as QueryRequest
    fields (reference QueryRequest ColumnAttrs/ExcludeColumns/
    ExcludeRowAttrs), equivalent to the JSON surface's URL params."""
    import json

    from pilosa_tpu.wire import pb2

    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query",
        b'Set(1, f=1) SetColumnAttrs(1, city="nyc") '
        b'SetRowAttrs(f, 1, team="blue")')
    p = pb2()
    qr = p.QueryRequest(query="Row(f=1)", column_attrs=True,
                        exclude_row_attrs=True)
    raw, ct = praw(
        "POST", f"{node}/index/i/query", qr.SerializeToString(),
        content_type="application/x-protobuf",
    )
    assert ct == "application/json"
    (out,) = json.loads(raw)["results"]
    assert out["attrs"] == {}  # excludeRowAttrs
    assert out["columns"] == [1]
    assert out["columnAttrs"] == [{"id": 1, "attrs": {"city": "nyc"}}]
