"""Elastic membership plane: graceful drain, join absorption, and the
split planner — the ISSUE-17 state machine end to end.

The invariants pinned here:

- a drain moves every group the target owns, hands off its CDC
  cursors, and removes it from the ring — with the data still
  byte-queryable from the survivors (replica_n == 1, so a lost group
  would be VISIBLY lost);
- the target sheds writes from the first broadcast until it departs,
  and STAYS read-only after "done" (a drained node is decommissioned,
  not recycled);
- one coordinated actuator per epoch: the autopilot skips (with a
  /debug/autopilot-visible reason) while a drain is active, a second
  drain is refused, and every refusal carries its reason;
- the record is resumable: any acting coordinator can adopt an ACTIVE
  record and finish the machine (coordinator failover mid-drain);
- the wire regression that motivated epoch-stamping: drain messages
  carry the CURRENT cluster epoch, because the drain's own moving step
  bumps the epoch past the record's minted-at-start one — a record
  ordered by (epoch, rev) must still be adoptable afterwards."""

import time
import urllib.error

import pytest

from cluster_helpers import join_node, make_cluster, req, seed, uri
from test_autopilot import _bare_cluster

from pilosa_tpu.autopilot import ElasticError, ElasticManager, plan_splits
from pilosa_tpu.autopilot.planner import Autopilot
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage.wal import WriteAheadLog

HALF = SHARD_WIDTH // 2


def _coordinator(servers):
    return next(s for s in servers if s.api.cluster.is_acting_coordinator)


def _wait(pred, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _mint_active_record(c, target, state="moving"):
    """Install a drain record as the coordinator would: epoch minted
    once at start, then gossiped (set_drain stamps the wire with the
    CURRENT cluster epoch)."""
    epoch = c._bump_epoch()
    record = {"epoch": epoch, "rev": 1, "target": target,
              "state": state, "coordinator": c.local.id,
              "groups": 0, "moved": 0, "error": ""}
    c.set_drain(record)
    return record


class TestPlanSplits:
    OWN = {("i", 0): ("a",), ("i", 1): ("b",)}

    def owners_of(self, ix, s):
        return self.OWN.get((ix, s), ())

    def test_hot_shard_splits_across_nodes(self):
        splits, merges = plan_splits(
            {("i", 0): 100.0, ("i", 1): 2.0}, self.owners_of,
            ["a", "b"], {}, split_threshold=1.5)
        assert merges == []
        assert len(splits) == 1
        s = splits[0]
        assert (s["index"], s["shard"]) == ("i", 0)
        # spans tile [0, SHARD_WIDTH) contiguously, one owner each
        spans = s["spans"]
        assert spans[0][0] == 0 and spans[-1][1] == SHARD_WIDTH
        assert all(spans[i][1] == spans[i + 1][0]
                   for i in range(len(spans) - 1))
        # the current owner keeps the first range (no data movement for
        # it) and the union NEVER shrinks below the current owners
        assert spans[0][2] == ("a",)
        assert s["owners"][0] == "a" and set(s["owners"]) == {"a", "b"}

    def test_disabled_threshold_merges_everything(self):
        current = {("i", 0): ((0, HALF, ("a",)),
                              (HALF, SHARD_WIDTH, ("b",)))}
        assert plan_splits({("i", 0): 100.0}, self.owners_of,
                           ["a", "b"], current,
                           split_threshold=0.0) == ([], [("i", 0)])

    def test_single_node_cannot_split(self):
        assert plan_splits({("i", 0): 100.0}, self.owners_of,
                           ["a"], {}, split_threshold=1.5) == ([], [])

    def test_hysteresis_merge(self):
        current = {("i", 0): ((0, HALF, ("a",)),
                              (HALF, SHARD_WIDTH, ("b",)))}
        # heat collapsed to near-zero: merged back
        _, merges = plan_splits(
            {("i", 0): 0.1, ("i", 1): 100.0}, self.owners_of,
            ["a", "b"], current, split_threshold=1.5)
        assert merges == [("i", 0)]
        # heat below the cut but above half of it: left alone (no
        # re-split either — already-split shards are skipped)
        splits, merges = plan_splits(
            {("i", 0): 60.0, ("i", 1): 40.0}, self.owners_of,
            ["a", "b"], current, split_threshold=1.5)
        assert merges == []
        assert all((s["index"], s["shard"]) != ("i", 0) for s in splits)

    def test_split_ways_clamped_to_membership(self):
        splits, _ = plan_splits(
            {("i", 0): 100.0, ("i", 1): 2.0}, self.owners_of,
            ["a", "b", "c"], {}, split_threshold=1.5, split_ways=16)
        assert len(splits[0]["spans"]) == 3

    def test_replica_width_spans(self):
        # replica_n > 1 widens each span's owner tuple so a narrowed
        # plain-Set write still lands on replica_n nodes; the union
        # (and with it data placement) is unchanged, and replica_n=1
        # degenerates to the original single-owner spans byte-for-byte
        one, _ = plan_splits(
            {("i", 0): 100.0, ("i", 1): 2.0}, self.owners_of,
            ["a", "b", "c"], {}, split_threshold=1.5, replica_n=1)
        assert all(len(ids) == 1 for _lo, _hi, ids in one[0]["spans"])
        two, _ = plan_splits(
            {("i", 0): 100.0, ("i", 1): 2.0}, self.owners_of,
            ["a", "b", "c"], {}, split_threshold=1.5, replica_n=2)
        spans = two[0]["spans"]
        assert all(len(ids) == 2 for _lo, _hi, ids in spans)
        # same tiling and same lead owner per span as the replica_n=1
        # plan; the extra replica is the next node round-robin
        assert [(lo, hi) for lo, hi, _ in spans] \
            == [(lo, hi) for lo, hi, _ in one[0]["spans"]]
        assert [ids[0] for _lo, _hi, ids in spans] \
            == [ids[0] for _lo, _hi, ids in one[0]["spans"]]
        assert two[0]["owners"] == one[0]["owners"]
        # width clamps to the spread: replica_n beyond membership
        wide, _ = plan_splits(
            {("i", 0): 100.0, ("i", 1): 2.0}, self.owners_of,
            ["a", "b"], {}, split_threshold=1.5, replica_n=5)
        assert all(len(ids) == 2 for _lo, _hi, ids in wide[0]["spans"])


class TestDepartedCursors:
    def test_wal_drops_only_the_departed_members_cursors(self, tmp_path):
        wal = WriteAheadLog(str(tmp_path))
        wal.register_cursor("tailer:n9", 5)
        wal.register_cursor("follower:n9", 3)
        wal.register_cursor("tailer:n3", 7)
        assert wal.drop_cursors_for("n9") == 2
        assert wal.drop_cursors_for("n9") == 0  # idempotent
        assert wal.cursors() == {"tailer:n3": 7}
        assert wal.metrics()["cdc_cursors_dropped_total"] == 2


class TestDrainEndToEnd:
    def test_drain_moves_data_sheds_writes_and_leaves(self, tmp_path):
        servers = make_cluster(tmp_path, 3, replica_n=1)
        try:
            seed(servers[0])
            coord = _coordinator(servers)
            coord.api.elastic.LEAVE_TIMEOUT = 5.0
            victim = next(s for s in reversed(servers) if s is not coord)
            vname = victim.config.name
            before = req("POST", f"{uri(coord)}/index/i/query",
                         b"Count(Row(f=1))")["results"][0]
            assert before == 24
            # a cursor the victim registered on the coordinator's WAL:
            # the handoff step must release the retention it pins
            wal = coord.api.holder.wal
            if wal is not None:
                wal.register_cursor(f"tailer:{vname}", 0)

            out = req("POST", f"{uri(coord)}/cluster/drain/{vname}", b"")
            assert out["state"] == "pending" and out["target"] == vname

            c = coord.api.cluster
            assert _wait(lambda: c.drain_record.get("state") == "done",
                         timeout=45), c.drain_record
            assert c.drain_record.get("error") == ""

            # the target left the ring — deliberately (never rejoins)
            assert _wait(lambda: vname not in c.nodes, timeout=10)
            assert victim.api.cluster._left
            assert sorted(c.nodes) == sorted(
                s.config.name for s in servers if s is not victim)

            # data intact on the survivors, at replica_n == 1
            assert _wait(lambda: c.state == "NORMAL", timeout=30)
            got = req("POST", f"{uri(coord)}/index/i/query",
                      b"Count(Row(f=1))")["results"][0]
            assert got == before
            # no survivor's placement names the departed node
            for s in servers:
                if s is victim:
                    continue
                for ids in s.api.cluster.placement.snapshot().values():
                    assert vname not in ids

            # a drained node is read-only FOREVER: done + _left
            assert victim.api.cluster.draining
            with pytest.raises(urllib.error.HTTPError) as err:
                req("POST", f"{uri(victim)}/index/i/query",
                    b"Set(1, f=1)")
            assert err.value.code == 503

            m = coord.api.elastic.metrics()
            assert m["elastic_drains_started_total"] == 1
            assert m["elastic_drains_completed_total"] == 1
            assert m["elastic_drain_active"] == 0
            if wal is not None:
                assert m["elastic_cursor_handoffs_total"] >= 1
                assert f"tailer:{vname}" not in wal.cursors()

            # the inspectors surface the machine on every node
            status = req("GET", f"{uri(coord)}/cluster/drain")
            assert status["drain"]["state"] == "done"
            assert status["active"] is False
            insp = req("GET", f"{uri(coord)}/debug/elastic")
            assert insp["enabled"] is True
            assert insp["metrics"]["elastic_drains_completed_total"] == 1
        finally:
            for s in servers:
                s.close()


class TestRefusals:
    def test_refusal_reasons(self, tmp_path):
        servers = make_cluster(tmp_path, 3, replica_n=1)
        try:
            coord = _coordinator(servers)
            other = next(s for s in servers if s is not coord)

            with pytest.raises(ElasticError, match="acting coordinator"):
                other.api.elastic.start_drain(coord.config.name)
            with pytest.raises(ElasticError) as err:
                coord.api.elastic.start_drain("no-such-node")
            assert err.value.status == 404
            with pytest.raises(ElasticError,
                               match="refusing to drain the acting"):
                coord.api.elastic.start_drain(coord.config.name)

            # the HTTP edge maps ElasticError to its carried status
            with pytest.raises(urllib.error.HTTPError) as herr:
                req("POST", f"{uri(coord)}/cluster/drain/no-such-node",
                    b"")
            assert herr.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as herr:
                req("DELETE", f"{uri(coord)}/cluster/drain")
            assert herr.value.code == 409  # no drain in flight
        finally:
            for s in servers:
                s.close()

    def test_drain_and_autopilot_mutually_exclude(self, tmp_path):
        """One coordinated actuator per epoch: with a drain record
        ACTIVE the autopilot pass skips (reason on /debug/autopilot)
        and a second drain is refused; after the abort both resume."""
        servers = make_cluster(tmp_path, 3, replica_n=1,
                               autopilot_enabled=True,
                               autopilot_interval=3600)
        try:
            coord = _coordinator(servers)
            c = coord.api.cluster
            target = next(s.config.name for s in servers if s is not coord)
            _mint_active_record(c, target)
            assert c.drain_active

            rec = coord.api.autopilot.run_pass()
            assert rec == {"acted": False, "reason": "drain-in-flight"}
            out = req("GET", f"{uri(coord)}/debug/autopilot")
            assert out["skips"].get("drain-in-flight", 0) >= 1

            with pytest.raises(ElasticError, match="already in flight"):
                coord.api.elastic.start_drain(target)

            # the record gossiped: the TARGET is shedding writes now,
            # before any data moved
            victim = next(s for s in servers
                          if s.config.name == target)
            assert _wait(lambda: victim.api.cluster.draining, timeout=5)

            aborted = coord.api.elastic.abort_drain()
            assert aborted["state"] == "aborted"
            assert not c.drain_active
            assert _wait(lambda: not victim.api.cluster.draining,
                         timeout=5)
            with pytest.raises(ElasticError, match="no drain in flight"):
                coord.api.elastic.abort_drain()
        finally:
            for s in servers:
                s.close()


class TestResume:
    def test_departed_target_record_is_stamped_done(self):
        c = _bare_cluster(["n0"])
        em = ElasticManager(c)
        epoch = c._bump_epoch()
        c.drain_record = {"epoch": epoch, "rev": 2, "target": "gone",
                          "state": "moving", "coordinator": "n9",
                          "groups": 1, "moved": 0, "error": ""}
        assert em.maybe_resume() is True
        assert c.drain_record["state"] == "done"
        assert em.drains_completed == 1
        assert em.maybe_resume() is False  # terminal: nothing to do

    def test_inactive_record_is_ignored(self):
        c = _bare_cluster(["n0"])
        em = ElasticManager(c)
        assert em.maybe_resume() is False
        c.drain_record = {"epoch": 1024, "rev": 9, "target": "n0",
                          "state": "aborted"}
        assert em.maybe_resume() is False

    def test_failover_coordinator_finishes_a_leaving_drain(self,
                                                           tmp_path):
        """The resumability contract: a record parked in "leaving"
        (its coordinator died right after the handoff step) is adopted
        by the acting coordinator's maybe_resume — the heartbeat-tick
        hook — and driven to done, with the target actually leaving."""
        servers = make_cluster(tmp_path, 3, replica_n=1)
        try:
            coord = _coordinator(servers)
            c = coord.api.cluster
            coord.api.elastic.LEAVE_TIMEOUT = 5.0
            victim = next(s for s in reversed(servers) if s is not coord)
            vname = victim.config.name
            # the record claims a DEAD coordinator minted it mid-drain
            epoch = c._bump_epoch()
            c.set_drain({"epoch": epoch, "rev": 4, "target": vname,
                         "state": "leaving", "coordinator": "departed",
                         "groups": 0, "moved": 0, "error": ""})

            assert coord.api.elastic.maybe_resume() is True
            assert coord.api.elastic.drains_resumed == 1
            assert _wait(lambda: c.drain_record.get("state") == "done",
                         timeout=20), c.drain_record
            assert _wait(lambda: vname not in c.nodes, timeout=10)
            assert victim.api.cluster._left
        finally:
            for s in servers:
                s.close()


class TestWireEpochRegression:
    def test_drain_update_survives_the_moving_steps_epoch_bump(self):
        """The bug the stamp fixed: the drain's own moving step mints
        newer cluster epochs (placement + resize), so a drain-update
        stamped with the record's start epoch would be FENCED as stale
        by every peer. The wire must carry the CURRENT epoch; the
        record's (epoch, rev) pair orders copies inside adopt_drain."""
        c = _bare_cluster(["n0", "n1"])
        record = {"epoch": 1024, "rev": 1, "target": "n1",
                  "state": "pending", "coordinator": "n0",
                  "groups": 0, "moved": 0, "error": ""}
        c.handle_message({"type": "drain-update", "epoch": 1024,
                          "drain": dict(record)})
        assert c.drain_record["state"] == "pending"

        # the moving step bumped the cluster epoch well past 1024
        c.handle_message({"type": "cluster-state", "state": "NORMAL",
                          "epoch": 9216})
        assert c.epoch == 9216

        # a state advance of the SAME drain, correctly stamped with the
        # current epoch, must be adopted via its higher rev
        record["rev"], record["state"] = 4, "handoff"
        c.handle_message({"type": "drain-update", "epoch": 9216,
                          "drain": dict(record)})
        assert c.drain_record["state"] == "handoff"

        # while a genuinely STALE SENDER (the healed ex-coordinator
        # replaying the old wire epoch) is fenced unapplied
        rejects = c.stale_epoch_rejects
        stale = dict(record, rev=9, state="aborted")
        c.handle_message({"type": "drain-update", "epoch": 1024,
                          "drain": stale})
        assert c.drain_record["state"] == "handoff"
        assert c.stale_epoch_rejects == rejects + 1

    def test_drain_leave_targets_only_the_named_node(self):
        c = _bare_cluster(["n0", "n1"])
        c.handle_message({"type": "drain-leave", "node": "n1",
                          "epoch": c.epoch})
        time.sleep(0.2)
        assert not c._left  # addressed to n1, we are n0
        c.handle_message({"type": "drain-leave", "node": "n0",
                          "epoch": c.epoch})
        assert _wait(lambda: c._left, timeout=5)


class TestJoinAbsorption:
    def test_joiner_byte_verifies_its_warmed_copy(self, tmp_path):
        """Join warm-up: the inventory fetch byte-verifies each fetched
        fragment against its source (warm_verified counts) before the
        freshness diff may skip it — and with cluster heat present the
        fetch order is hottest-first (warm_heat_ordered counts)."""
        servers = make_cluster(tmp_path, 2, replica_n=1)
        late = None
        try:
            seed(servers[0])
            for _ in range(12):  # heat so the joiner has a warm order
                req("POST", f"{uri(servers[0])}/index/i/query",
                    b"Count(Row(f=1))")
            late = join_node(tmp_path, servers[0], replica_n=1)
            assert late.api.cluster.wait_until_normal(30)
            c = late.api.cluster
            assert _wait(
                lambda: c.warm_verified + c.warm_verify_failed > 0,
                timeout=20)
            # verified copies serve reads; failures would have been
            # left to the freshness diff (still correct, just slower)
            assert c.warm_verified > 0
            got = req("POST", f"{uri(late)}/index/i/query",
                      b"Count(Row(f=1))")["results"][0]
            assert got == 24
            metrics = c.metrics()
            assert metrics["elastic_warm_verified_total"] == \
                c.warm_verified
        finally:
            if late is not None:
                late.close()
            for s in servers:
                s.close()
