"""M1 storage-tree tests (modeled on the reference's fragment_test.go /
field_test.go / index_test.go / holder_test.go coverage — SURVEY.md §4):
temp-dir fragments, set/clear round-trips, durability (op log + snapshot),
checksum blocks, field-type semantics, holder reopen."""

import datetime as dt

import numpy as np
import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import Field, FieldOptions, Fragment, Holder
from pilosa_tpu.storage.field import BSI_EXISTS_ROW, BSI_OFFSET_ROW
from pilosa_tpu.storage.view import (
    VIEW_STANDARD,
    views_by_time_range,
    views_for_time,
)


@pytest.fixture
def frag(tmp_path):
    f = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0).open()
    yield f
    f.close()


class TestFragment:
    def test_set_clear_roundtrip(self, frag):
        assert frag.set_bit(3, 100)
        assert not frag.set_bit(3, 100)  # already set
        assert frag.contains(3, 100)
        assert frag.count_row(3) == 1
        assert frag.clear_bit(3, 100)
        assert not frag.clear_bit(3, 100)
        assert frag.count_row(3) == 0

    def test_row_words_and_device_row(self, frag):
        cols = [0, 7, 31, 32, 65535, 65536, SHARD_WIDTH - 1]
        for c in cols:
            frag.set_bit(2, c)
        words = frag.row_words(2)
        from pilosa_tpu.ops.packing import unpack_bits

        np.testing.assert_array_equal(unpack_bits(words), np.array(cols, np.uint64))
        dev = np.asarray(frag.device_row(2))
        np.testing.assert_array_equal(dev, words)

    def test_persistence_and_oplog(self, tmp_path):
        path = str(tmp_path / "5")
        f = Fragment(path, "i", "f", "standard", 5).open()
        f.bulk_import([1, 1, 2], [10, 20, 30])
        f.set_bit(9, 99)
        f.clear_bit(1, 10)
        f.close()

        f2 = Fragment(path, "i", "f", "standard", 5).open()
        assert not f2.contains(1, 10)
        assert f2.contains(1, 20)
        assert f2.contains(2, 30)
        assert f2.contains(9, 99)
        assert f2.op_n == 3  # bulk + set + clear replayed from the log
        f2.close()

    def test_snapshot_compacts(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, "i", "f", "standard", 0, snapshot_threshold=5).open()
        for i in range(12):
            f.set_bit(0, i)
        assert f.op_n <= 5  # crossed threshold -> compacted
        f.close()
        f2 = Fragment(path, "i", "f", "standard", 0).open()
        assert f2.count_row(0) == 12
        f2.close()

    def test_bulk_import_and_rowids(self, frag):
        rows = np.repeat([0, 4, 7], 1000)
        pos = np.tile(np.arange(1000) * 37 % SHARD_WIDTH, 3)
        changed = frag.bulk_import(rows, pos)
        assert changed == len(np.unique((rows.astype(np.uint64) << np.uint64(20)) + pos))
        assert frag.row_ids() == [0, 4, 7]
        assert frag.max_row_id() == 7

    def test_import_roaring(self, frag):
        from pilosa_tpu.roaring import RoaringBitmap, serialize

        other = RoaringBitmap.from_ids([(1 << 20) + 5, (1 << 20) + 6, 3])
        assert frag.import_roaring(serialize(other)) == 3
        assert frag.contains(1, 5) and frag.contains(1, 6) and frag.contains(0, 3)

    def test_blocks_checksums(self, frag):
        frag.set_bit(0, 1)
        frag.set_bit(99, 1)   # same block (rows 0-99)
        frag.set_bit(100, 1)  # next block
        blocks = dict(frag.blocks())
        assert set(blocks) == {0, 1}
        before = blocks[0]
        frag.set_bit(5, 5)
        assert dict(frag.blocks())[0] != before
        assert dict(frag.blocks())[1] == blocks[1]
        np.testing.assert_array_equal(
            frag.block_ids(1), np.array([(100 << 20) + 1], np.uint64)
        )

    def test_top_pairs(self, frag):
        for row, n in [(1, 5), (2, 50), (3, 20)]:
            frag.bulk_import([row] * n, list(range(n)))
        assert frag.top(2) == [(2, 50), (3, 20)]
        assert frag.top(10, row_ids=[1, 3]) == [(3, 20), (1, 5)]

    def test_write_row_words(self, frag):
        frag.set_bit(0, 1)
        from pilosa_tpu.ops.packing import pack_shard_row

        frag.write_row_words(0, pack_shard_row([2, 3]))
        assert not frag.contains(0, 1)
        assert frag.contains(0, 2) and frag.contains(0, 3)

    def test_position_validation(self, frag):
        with pytest.raises(ValueError):
            frag.set_bit(0, SHARD_WIDTH)
        with pytest.raises(ValueError):
            frag.bulk_import([0], [SHARD_WIDTH + 3])


class TestFieldTypes:
    def test_set_field(self, tmp_path):
        f = Field(str(tmp_path / "f"), "i", "f").open()
        assert f.set_bit(1, 10)
        assert f.set_bit(2, 10)  # multi-value ok
        frag = f.view(VIEW_STANDARD).fragment(0)
        assert frag.contains(1, 10) and frag.contains(2, 10)
        f.close()

    def test_mutex_field(self, tmp_path):
        f = Field(str(tmp_path / "m"), "i", "m", FieldOptions(type="mutex")).open()
        f.set_bit(1, 10)
        f.set_bit(2, 10)  # clears row 1 for column 10
        frag = f.view(VIEW_STANDARD).fragment(0)
        assert not frag.contains(1, 10)
        assert frag.contains(2, 10)
        f.close()

    def test_bool_field(self, tmp_path):
        f = Field(str(tmp_path / "b"), "i", "b", FieldOptions(type="bool")).open()
        f.set_bit(1, 7)
        f.set_bit(0, 7)
        frag = f.view(VIEW_STANDARD).fragment(0)
        assert frag.contains(0, 7) and not frag.contains(1, 7)
        with pytest.raises(ValueError):
            f.set_bit(2, 7)
        f.close()

    def test_int_field_roundtrip(self, tmp_path):
        f = Field(
            str(tmp_path / "v"), "i", "v", FieldOptions(type="int", min=-10, max=1000)
        ).open()
        for col, val in [(0, -10), (1, 0), (2, 777), (3, 1000), (1 << 20, 5)]:
            f.set_value(col, val)
        for col, val in [(0, -10), (1, 0), (2, 777), (3, 1000), (1 << 20, 5)]:
            assert f.value(col) == (val, True)
        assert f.value(99) == (0, False)
        # overwrite clears stale plane bits
        f.set_value(2, 1)
        assert f.value(2) == (1, True)
        with pytest.raises(ValueError):
            f.set_value(0, 1001)
        f.clear_value(3)
        assert f.value(3) == (0, False)
        f.close()

    def test_int_field_planes(self, tmp_path):
        f = Field(
            str(tmp_path / "v"), "i", "v", FieldOptions(type="int", min=0, max=7)
        ).open()
        f.set_value(4, 5)  # 0b101
        frag = f.view(f.bsi_view_name()).fragment(0)
        assert frag.contains(BSI_EXISTS_ROW, 4)
        assert frag.contains(BSI_OFFSET_ROW + 0, 4)
        assert not frag.contains(BSI_OFFSET_ROW + 1, 4)
        assert frag.contains(BSI_OFFSET_ROW + 2, 4)
        f.close()

    def test_time_field_views(self, tmp_path):
        f = Field(
            str(tmp_path / "t"), "i", "t",
            FieldOptions(type="time", time_quantum="YMD"),
        ).open()
        ts = dt.datetime(2019, 1, 2, 15)
        f.set_bit(1, 10, timestamp=ts)
        assert set(f.views) >= {
            "standard", "standard_2019", "standard_201901", "standard_20190102",
        }
        f.close()

    def test_field_meta_persistence(self, tmp_path):
        Field(
            str(tmp_path / "v"), "i", "v", FieldOptions(type="int", min=3, max=9)
        ).open().close()
        f2 = Field(str(tmp_path / "v"), "i", "v").open()
        assert f2.options.type == "int"
        assert (f2.options.min, f2.options.max) == (3, 9)
        f2.close()


class TestTimeViewNames:
    def test_views_for_time(self):
        ts = dt.datetime(2019, 1, 2, 15)
        assert views_for_time("standard", "YMDH", ts) == [
            "standard_2019", "standard_201901", "standard_20190102",
            "standard_2019010215",
        ]

    def test_views_by_time_range_minimal_cover(self):
        got = views_by_time_range(
            "standard", "YMD",
            dt.datetime(2018, 12, 30), dt.datetime(2019, 2, 2),
        )
        assert got == [
            "standard_20181230", "standard_20181231", "standard_201901",
            "standard_20190201",
        ]

    def test_views_by_time_range_full_years(self):
        got = views_by_time_range(
            "standard", "YMDH", dt.datetime(2018, 1, 1), dt.datetime(2020, 1, 1)
        )
        assert got == ["standard_2018", "standard_2019"]


class TestHolder:
    def test_create_open_reopen(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        idx = h.create_index("stars")
        f = idx.create_field("stargazer")
        f.set_bit(1, 100)
        f.set_bit(1, SHARD_WIDTH + 5)  # second shard
        idx.mark_columns_exist([100, SHARD_WIDTH + 5])
        assert idx.available_shards() == [0, 1]
        h.close()

        h2 = Holder(str(tmp_path / "data")).open()
        idx2 = h2.index("stars")
        assert idx2 is not None
        f2 = idx2.field("stargazer")
        assert f2.view(VIEW_STANDARD).fragment(0).contains(1, 100)
        assert f2.view(VIEW_STANDARD).fragment(1).contains(1, 5)
        ex = idx2.existence_fragment(0)
        assert ex.contains(0, 100)
        assert [i["name"] for i in h2.schema()] == ["stars"]
        h2.close()

    def test_delete_index_and_field(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        idx = h.create_index("a")
        idx.create_field("x")
        idx.delete_field("x")
        assert idx.field("x") is None
        h.delete_index("a")
        assert h.index("a") is None
        h2 = Holder(str(tmp_path / "data")).open()
        assert h2.schema() == []
        h.close(); h2.close()

    def test_invalid_names(self, tmp_path):
        h = Holder(str(tmp_path / "data")).open()
        with pytest.raises(ValueError):
            h.create_index("9bad")
        idx = h.create_index("ok")
        with pytest.raises(ValueError):
            idx.create_field("_internal")
        h.close()


def test_concurrent_fragment_writes_do_not_lose_updates(tmp_path):
    """Per-fragment lock (reference fragment.mu): N threads hammering the
    same fragment must land every bit and keep the op log coherent through
    snapshot + reopen."""
    import threading

    from pilosa_tpu.storage.fragment import Fragment

    frag = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0,
                    snapshot_threshold=64).open()
    n_threads, per_thread = 8, 200
    errs = []

    def worker(t):
        try:
            for k in range(per_thread):
                frag.set_bit(t, k * 7 % (1 << 20))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    want_per_row = len({k * 7 % (1 << 20) for k in range(per_thread)})
    for t in range(n_threads):
        assert frag.count_row(t) == want_per_row, t
    frag.close()
    # reopen: snapshot + op log replay reproduce the same state
    frag2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0).open()
    for t in range(n_threads):
        assert frag2.count_row(t) == want_per_row, t
    frag2.close()


class TestRowCounts:
    def test_row_counts_matches_per_row_oracle(self, tmp_path):
        from pilosa_tpu.storage import Holder

        holder = Holder(str(tmp_path / "d")).open()
        f = holder.create_index("i").create_field("f")
        frag = f.view("standard", create=True).fragment(0, create=True)
        rng = np.random.default_rng(3)
        rows = rng.integers(0, 5000, 4000, dtype=np.uint64)
        poss = rng.integers(0, 1 << 20, 4000, dtype=np.uint64)
        frag.bulk_import(rows, poss)
        got_rows, got_counts = frag.row_counts()
        want = {}
        for r in np.unique(rows).tolist():
            c = frag.count_row(int(r))
            if c:
                want[int(r)] = c
        assert dict(zip(got_rows.tolist(), got_counts.tolist())) == want
        holder.close()

    def test_row_counts_empty(self, tmp_path):
        from pilosa_tpu.storage import Holder

        holder = Holder(str(tmp_path / "d")).open()
        f = holder.create_index("i").create_field("f")
        frag = f.view("standard", create=True).fragment(0, create=True)
        rows, counts = frag.row_counts()
        assert rows.size == 0 and counts.size == 0
        holder.close()

    def test_discovery_paths_avoid_per_row_counts(self, tmp_path, monkeypatch):
        """Rows() discovery and cold-cache TopN phase 1 must not call
        count_row per row (VERDICT r1 weak #5: multi-second host loops at
        50k rows x 1k shards)."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.storage import Holder
        from pilosa_tpu.storage.cache import CACHE_TYPE_NONE
        from pilosa_tpu.storage import FieldOptions
        from pilosa_tpu.storage.fragment import Fragment

        holder = Holder(str(tmp_path / "d")).open()
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f", FieldOptions(cache_type=CACHE_TYPE_NONE))
        rng = np.random.default_rng(4)
        seen = set()
        for s in range(4):
            frag = f.view("standard", create=True).fragment(s, create=True)
            rows = rng.integers(0, 2000, 3000, dtype=np.uint64)
            seen.update(rows.tolist())
            frag.bulk_import(rows, rng.integers(0, 1 << 20, 3000, dtype=np.uint64))
        ex = Executor(holder)
        calls = {"n": 0}
        orig = Fragment.count_row

        def counting(self, row):
            calls["n"] += 1
            return orig(self, row)

        monkeypatch.setattr(Fragment, "count_row", counting)
        (rows_res,) = ex.execute("i", "Rows(f)")
        assert rows_res == sorted(seen)
        assert calls["n"] == 0  # discovery is metadata-only
        # cold-cache TopN phase 1: fragment.top falls back to row_counts
        pairs = f.view("standard").fragment(0).top(5)
        assert len(pairs) == 5 and calls["n"] == 0
        holder.close()


class TestBatchedBSIImport:
    def _mk(self, tmp_path, lo=-10, hi=1000):
        from pilosa_tpu.storage.field import Field

        return Field(
            str(tmp_path / "v"), "i", "v",
            FieldOptions(type="int", min=lo, max=hi),
        ).open()

    def test_matches_set_value_loop(self, tmp_path):
        """import_values == a sequential set_value loop: same final
        values, same changed count, incl. overwrites of existing columns
        and in-batch duplicates (last wins)."""
        import numpy as np

        rng = np.random.default_rng(5)
        a = self._mk(tmp_path / "a")
        b = self._mk(tmp_path / "b")
        cols = rng.integers(0, 3 * (1 << 20), 400, dtype=np.uint64)
        vals = rng.integers(-10, 1001, 400, dtype=np.int64)
        # two waves so the second overwrites some of the first
        for wave in (slice(0, 250), slice(150, 400)):
            loop_changed = 0
            seen = {}
            for c, v in zip(cols[wave].tolist(), vals[wave].tolist()):
                loop_changed += a.set_value(int(c), int(v))
                seen[int(c)] = int(v)
            batch_changed = b.import_values(cols[wave], vals[wave])
            assert batch_changed == loop_changed
            for c, v in seen.items():
                assert a.value(c) == (v, True)
                assert b.value(c) == (v, True), c
        a.close()
        b.close()

    def test_duplicate_columns_last_wins(self, tmp_path):
        f = self._mk(tmp_path)
        assert f.import_values([7, 7, 7], [5, 900, 42]) == 1
        assert f.value(7) == (42, True)
        # unchanged re-import reports zero
        assert f.import_values([7], [42]) == 0
        f.close()

    def test_range_validation(self, tmp_path):
        import pytest

        f = self._mk(tmp_path)
        with pytest.raises(ValueError, match="outside field range"):
            f.import_values([1, 2], [5, 2000])
        # nothing applied
        assert f.value(1) == (0, False)
        f.close()


class TestMutexBulkImport:
    def test_import_clears_previous_rows(self, tmp_path):
        """Bulk import into a mutex field preserves the single-value
        invariant: each imported column's previous row is cleared
        (reference bulkImportMutex). Previously plain bulk_import left
        columns set in SEVERAL rows."""
        import numpy as np

        from pilosa_tpu.storage.field import Field

        f = Field(str(tmp_path / "m"), "i", "m",
                  FieldOptions(type="mutex")).open()
        frag = f.view("standard", create=True).fragment(0, create=True)
        for col, row in [(5, 1), (6, 1), (7, 2)]:
            f.set_bit(row, col)
        # move 5 -> row 2, keep 6, add 8 -> row 3; duplicate col 9 keeps last
        changed = frag.import_mutex(
            np.array([2, 1, 3, 1, 2], np.uint64),
            np.array([5, 6, 8, 9, 9], np.uint64),
        )
        assert changed == 3  # 5 moved, 8 new, 9 new (6 was a no-op)
        got = {r: frag.row_columns(r).tolist() for r in frag.row_ids()}
        got = {r: c for r, c in got.items() if c}
        assert got == {1: [6], 2: [5, 7, 9], 3: [8]}
        f.close()

    def test_api_routes_mutex_and_bool_imports(self, tmp_path):
        from pilosa_tpu.server.api import API, ApiError

        holder = Holder(str(tmp_path / "h")).open()
        idx = holder.create_index("i")
        idx.create_field("m", FieldOptions(type="mutex"))
        idx.create_field("b", FieldOptions(type="bool"))
        api = API(holder)
        from pilosa_tpu.executor import Executor

        ex = Executor(holder)
        ex.execute("i", "Set(5, m=1)")
        api.import_bits("i", "m", [2], [5])
        assert ex.execute("i", "Row(m=1)")[0].columns().tolist() == []
        assert ex.execute("i", "Row(m=2)")[0].columns().tolist() == [5]
        api.import_bits("i", "b", [1, 0, 1], [10, 11, 10])
        assert ex.execute("i", "Row(b=true)")[0].columns().tolist() == [10]
        assert ex.execute("i", "Row(b=false)")[0].columns().tolist() == [11]
        import pytest

        with pytest.raises(ApiError, match="bool field rows"):
            api.import_bits("i", "b", [2], [12])
        holder.close()
