"""Adversarial TopN approximation tests (VERDICT r5 Next #7).

TopN's phase-1 candidate set comes from the per-fragment RANKED CACHES,
ordered by UNFILTERED row counts; phase 2 recounts candidates exactly.
Two consequences, pinned here and documented in docs/PQL.md:

- A FILTERED TopN considers only each fragment's top
  ``max(4n, n+10)`` rows by UNFILTERED count — it can miss a row
  entirely, even the true #1 under the filter, when that row's
  unfiltered count ranks below the candidate window (and below the
  cache's kept set when ``cacheSize`` overflowed). This is the
  reference's documented cache approximation.
- A fully COLD cache (crash before cache save, `recalculate-caches` not
  yet run) does not ADD error: `fragment.top()` falls back to the exact
  container-metadata scan, so unfiltered TopN stays exact; the filtered
  candidate-window bound above applies cold or warm.
- The escape hatch is always `TopN(ids=[...])` (phase 2 only, exact) —
  or `Rows(f)` + `TopN(ids=)` as the exact-but-slower oracle.
"""

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.view import VIEW_STANDARD

CACHE_SIZE = 8
N_DECOYS = 20          # rows 1..20: high unfiltered count, miss the filter
NEEDLE = 21            # row 21: low unfiltered count, IS the filtered top
NEEDLE_BITS = 30


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    f = idx.create_field(
        "f", FieldOptions.from_dict({"cacheType": "ranked",
                                     "cacheSize": CACHE_SIZE}))
    g = idx.create_field("g")
    frag = f.view(VIEW_STANDARD, create=True).fragment(0, create=True)
    # decoys: 100 bits each in columns 0..1999 (outside the filter)
    for row in range(1, N_DECOYS + 1):
        frag.bulk_import(np.full(100, row, np.uint64),
                         np.arange(100, dtype=np.uint64) * 20 + row)
    # the needle: NEEDLE_BITS bits, all inside the filter region
    needle_cols = 10_000 + np.arange(NEEDLE_BITS, dtype=np.uint64)
    frag.bulk_import(np.full(NEEDLE_BITS, NEEDLE, np.uint64), needle_cols)
    # filter row g=1 covers exactly the needle's columns
    gfrag = g.view(VIEW_STANDARD, create=True).fragment(0, create=True)
    gfrag.bulk_import(np.full(NEEDLE_BITS, 1, np.uint64), needle_cols)
    ex = Executor(holder)
    yield holder, ex, frag
    holder.close()


def exact_filtered_topn(ex, n):
    """Oracle: Rows() enumeration + exact per-row recount (the ids= form
    skips phase 1 entirely), trimmed like TopN orders."""
    rows = ex.execute("i", "Rows(f)")[0]
    pairs = ex.execute(
        "i", f"TopN(f, Row(g=1), ids={list(rows)}, n=0)")[0]
    return [(p.id, p.count) for p in pairs[:n]]


def test_trimmed_cache_misses_filtered_top_row(env):
    """The adversarial bound: the cache trimmed to the top-8 unfiltered
    rows cannot supply the needle as a candidate, so the filtered TopN
    MISSES the true top row. The oracle proves the divergence."""
    holder, ex, frag = env
    got = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0]
    # phase 1 trimmed the cache (lazy, on first top()) and the needle
    # fell out of rank — so the filtered TopN cannot see it
    cached = set(frag.row_cache.ids())
    assert len(cached) <= CACHE_SIZE          # trim really happened
    assert NEEDLE not in cached               # needle fell out of rank
    assert all(p.id != NEEDLE for p in got)   # the approximation, pinned
    # exact answer (Rows + recount): needle first, with all its bits
    assert exact_filtered_topn(ex, 1) == [(NEEDLE, NEEDLE_BITS)]


def test_unfiltered_topn_stays_exact_despite_trim(env):
    """Without a filter the kept top-`cacheSize` rows contain every true
    top-n for n ≤ cacheSize − overlap: the decoys tie at 100 and order
    by ascending id, exactly what phase 2 returns."""
    holder, ex, frag = env
    got = ex.execute("i", "TopN(f, n=5)")[0]
    assert [(p.id, p.count) for p in got] == [
        (r, 100) for r in range(1, 6)
    ]


def test_cold_cache_falls_back_to_exact_scan(env):
    """Evict/cold the ranked cache entirely: fragment.top() falls back
    to the exact row_counts() metadata scan. Unfiltered TopN therefore
    stays EXACT on a cold cache — but the filtered candidate-window
    bound is a property of phase 1's overfetch, not of the cache, so
    the adversarial filtered query still misses the needle (its
    unfiltered rank stays below the window)."""
    holder, ex, frag = env
    frag.row_cache._counts.clear()            # crash-cold cache
    got = ex.execute("i", "TopN(f, n=5)")[0]
    assert [(p.id, p.count) for p in got] == [(r, 100) for r in range(1, 6)]
    frag.row_cache._counts.clear()
    got = ex.execute("i", "TopN(f, Row(g=1), n=1)")[0]
    assert all(p.id != NEEDLE for p in got)
    # the needle ranks 21st unfiltered; a window that REACHES its rank
    # makes the filtered query exact even cold (the bound, exactly)
    frag.row_cache._counts.clear()
    got = ex.execute("i", "TopN(f, Row(g=1), n=30)")[0]
    assert [(p.id, p.count) for p in got] == [(NEEDLE, NEEDLE_BITS)]


def test_recalculate_caches_restores_the_trimmed_regime(env):
    """The repair hatch recounts AND re-trims: after recalculate, the
    cache again holds the top unfiltered rows (approximate under the
    adversarial filter, exact without one) — recalculation fixes drift,
    it does not grow the bound."""
    holder, ex, frag = env
    frag.row_cache._counts.clear()
    frag.recalculate_cache()
    cached = set(frag.row_cache.ids())
    assert len(cached) <= CACHE_SIZE and NEEDLE not in cached
    got = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0]
    assert all(p.id != NEEDLE for p in got)
    got = ex.execute("i", "TopN(f, n=3)")[0]
    assert [(p.id, p.count) for p in got] == [(r, 100) for r in (1, 2, 3)]


def test_ids_form_is_always_exact(env):
    """`TopN(ids=[...])` bypasses phase 1, so it is exact regardless of
    cache state — the client-side escape hatch the docs point to."""
    holder, ex, frag = env
    got = ex.execute("i", f"TopN(f, Row(g=1), ids=[{NEEDLE}, 1], n=0)")[0]
    assert [(p.id, p.count) for p in got] == [(NEEDLE, NEEDLE_BITS)]


def test_quantized_ranking_adds_no_approximation(env):
    """`topn-quantized-ranking` is a WIRE optimization, not a second
    approximation layer: the 8-bit lane only reorders the candidate
    RANKING, and the widened window is recounted exactly — so the
    quantized DistExecutor matches the single-device Executor
    byte-for-byte on every TopN form, including the adversarial
    filtered shape (both lanes share phase 1's candidate window, so
    they share its documented bound — nothing more). verify_quantized
    re-runs the lossless recount in-process and raises on divergence,
    and ids= queries bypass the lane entirely (already an exact
    recount, nothing to rank)."""
    holder, ex, frag = env
    from pilosa_tpu.parallel import DistExecutor, make_mesh

    quant = DistExecutor(holder, make_mesh(2), quantized_ranking=True,
                         verify_quantized=True)
    for pql in ("TopN(f, n=5)",
                "TopN(f, n=3)",
                "TopN(f, Row(g=1), n=3)",
                "TopN(f, n=4, threshold=100)",
                f"TopN(f, Row(g=1), ids=[{NEEDLE}, 1], n=0)"):
        (want,) = ex.execute("i", pql)
        (got,) = quant.execute("i", pql)
        assert [(p.id, p.count) for p in got] == \
            [(p.id, p.count) for p in want], pql
