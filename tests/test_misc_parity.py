"""Backup/restore, TopN attr filters, Rows like=, /debug/pprof."""

import urllib.request

import pytest

from pilosa_tpu.cli import main
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import PQLError
from pilosa_tpu.storage import FieldOptions, Holder


def test_backup_restore_roundtrip(tmp_path, capsys):
    data_dir = str(tmp_path / "data")
    csv = tmp_path / "bits.csv"
    csv.write_text("1,10\n2,20\n")
    main(["import", "-i", "i", "-f", "f", "-d", data_dir, "--create", str(csv)])
    tarball = str(tmp_path / "backup.tar.gz")
    assert main(["backup", "-d", data_dir, "-o", tarball]) == 0
    restored = str(tmp_path / "restored")
    assert main(["restore", "-d", restored, "-i", tarball]) == 0
    capsys.readouterr()
    main(["export", "-i", "i", "-f", "f", "-d", restored])
    assert capsys.readouterr().out.splitlines() == ["1,10", "2,20"]
    # refuses to clobber a non-empty dir
    assert main(["restore", "-d", data_dir, "-i", tarball]) == 1


def test_topn_attr_filter(tmp_path):
    holder = Holder(str(tmp_path / "d")).open()
    ex = Executor(holder)
    idx = holder.create_index("i")
    f = idx.create_field("f")
    for row, n in [(1, 5), (2, 9), (3, 7)]:
        for c in range(n):
            f.set_bit(row, c)
    f.row_attrs.set_attrs(1, {"cat": "a"})
    f.row_attrs.set_attrs(2, {"cat": "b"})
    f.row_attrs.set_attrs(3, {"cat": "a"})
    (pairs,) = ex.execute("i", 'TopN(f, n=5, attrName="cat", attrValue="a")')
    assert [(p.id, p.count) for p in pairs] == [(3, 7), (1, 5)]
    holder.close()


def test_topn_attr_filter_bulk_read(tmp_path, monkeypatch):
    """The attr filter issues ONE bulk read for the whole candidate set
    (1k+ candidates), not a per-candidate attrs() loop — and bulk()
    chunks under SQLite's host-parameter limit."""
    holder = Holder(str(tmp_path / "d")).open()
    ex = Executor(holder)
    idx = holder.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_size=2048))
    n_rows = 1100
    for row in range(1, n_rows + 1):
        f.set_bit(row, row % 7)
        if row % 2:
            f.row_attrs.set_attrs(row, {"cat": "a"})

    calls = {"bulk": 0, "single": 0}
    real_bulk = f.row_attrs.bulk
    monkeypatch.setattr(
        f.row_attrs, "bulk",
        lambda ids: (calls.__setitem__("bulk", calls["bulk"] + 1),
                     real_bulk(ids))[1],
    )
    monkeypatch.setattr(
        f.row_attrs, "attrs",
        lambda id_: (_ for _ in ()).throw(
            AssertionError("per-candidate attrs() call in TopN filter")
        ),
    )
    (pairs,) = ex.execute(
        "i", f'TopN(f, n={n_rows}, attrName="cat", attrValue="a")'
    )
    assert calls["bulk"] == 1
    assert {p.id for p in pairs} == {r for r in range(1, n_rows + 1) if r % 2}
    holder.close()


def test_rows_like(tmp_path):
    holder = Holder(str(tmp_path / "d")).open()
    ex = Executor(holder)
    holder.create_index("i", keys=True).create_field(
        "tags", FieldOptions(keys=True)
    )
    for key in ("apple", "apricot", "banana", "grape"):
        ex.execute("i", f'Set("c1", tags="{key}")')
    assert ex.execute("i", 'Rows(tags, like="ap%")') == [["apple", "apricot"]]
    assert ex.execute("i", 'Rows(tags, like="%ap%")') == [
        ["apple", "apricot", "grape"]
    ]
    assert ex.execute("i", 'Rows(tags, like="%e")') == [["apple", "grape"]]
    holder.close()


def test_rows_like_requires_keys(tmp_path):
    holder = Holder(str(tmp_path / "d")).open()
    ex = Executor(holder)
    holder.create_index("i").create_field("f")
    with pytest.raises(PQLError):
        ex.execute("i", 'Rows(f, like="x%")')
    holder.close()


def test_debug_pprof(tmp_path):
    from pilosa_tpu.server.api import API
    from pilosa_tpu.server.http import serve_in_thread

    holder = Holder(str(tmp_path / "d")).open()
    server, port, _ = serve_in_thread(API(holder))
    with urllib.request.urlopen(f"http://localhost:{port}/debug/pprof") as r:
        text = r.read().decode()
    assert "--- thread" in text
    server.shutdown(); server.server_close(); holder.close()
