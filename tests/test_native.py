"""Native fastbits library tests: parity with the numpy fallback."""

import numpy as np
import pytest

from pilosa_tpu import native


requires_native = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain in environment"
)


@requires_native
def test_pack_unpack_popcount_parity():
    rng = np.random.default_rng(5)
    positions = np.unique(rng.choice(1 << 20, 50_000, replace=False)).astype(np.uint64)
    n_words = (1 << 20) // 32

    fast = native.pack_positions(positions, n_words)
    # numpy oracle
    bytes_ = np.zeros(n_words * 4, np.uint8)
    np.bitwise_or.at(
        bytes_,
        (positions >> np.uint64(3)).astype(np.int64),
        np.uint8(1) << (positions & np.uint64(7)).astype(np.uint8),
    )
    slow = bytes_.view("<u4")
    np.testing.assert_array_equal(fast, slow)

    assert native.popcount_words(fast) == positions.size
    np.testing.assert_array_equal(
        native.unpack_positions(fast, 0), positions
    )
    np.testing.assert_array_equal(
        native.unpack_positions(fast, 1 << 30), positions + (1 << 30)
    )


@requires_native
def test_runs_to_words():
    runs = np.array([[0, 5], [100, 100], [65530, 65535]], np.uint16)
    words = native.runs_to_words(runs)
    got = native.unpack_positions(words, 0).tolist()
    assert got == list(range(6)) + [100] + list(range(65530, 65536))


@requires_native
def test_empty_inputs():
    assert native.popcount_words(np.zeros(8, np.uint32)) == 0
    assert native.unpack_positions(np.zeros(8, np.uint32)).size == 0
    out = native.pack_positions(np.empty(0, np.uint64), 8)
    assert out.sum() == 0


def test_packing_api_works_with_or_without_native(monkeypatch):
    """pack_bits/unpack_bits give identical results on both paths."""
    from pilosa_tpu.ops import packing

    rng = np.random.default_rng(6)
    ids = np.unique(rng.choice(1 << 14, 1000, replace=False))
    with_native = packing.pack_bits(ids, 1 << 14)
    monkeypatch.setenv("PILOSA_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(native, "_lib", None)
    without = packing.pack_bits(ids, 1 << 14)
    np.testing.assert_array_equal(with_native, without)
    np.testing.assert_array_equal(
        packing.unpack_bits(without), ids.astype(np.uint64)
    )


def test_sorted_set_ops_match_numpy():
    """union/diff_sorted_u16 (the ARRAY-container import hot path) match
    the numpy set ops they replace, including empty and disjoint edges."""
    rng = np.random.default_rng(17)
    cases = [
        (np.empty(0, np.uint16), np.empty(0, np.uint16)),
        (np.array([3], np.uint16), np.empty(0, np.uint16)),
        (np.empty(0, np.uint16), np.array([9], np.uint16)),
        (np.array([1, 2, 3], np.uint16), np.array([4, 5], np.uint16)),
        (np.array([0, 65535], np.uint16), np.array([0, 65535], np.uint16)),
    ]
    for _ in range(20):
        a = np.unique(rng.choice(1 << 16, rng.integers(0, 4000),
                                 replace=False).astype(np.uint16))
        b = np.unique(rng.choice(1 << 16, rng.integers(0, 4000),
                                 replace=False).astype(np.uint16))
        cases.append((a, b))
    for a, b in cases:
        got_u = native.union_sorted_u16(a, b)
        got_d = native.diff_sorted_u16(a, b)
        if got_u is None:  # no toolchain: numpy fallback covers it
            continue
        np.testing.assert_array_equal(got_u, np.union1d(a, b))
        np.testing.assert_array_equal(
            got_d, np.setdiff1d(a, b, assume_unique=True)
        )
