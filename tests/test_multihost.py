"""Multi-host execution: 2 real processes, one global mesh over DCN.

The reference scales across nodes with HTTP fan-out + gossip (SURVEY.md
§2.4); the TPU framework's data plane scales by making the shard-axis
mesh span hosts under jax.distributed (SURVEY.md §7.2 M4/M6). This test
runs that path for real: two OS processes, each with 4 virtual CPU
devices, form an 8-device global mesh (gloo collectives over the
coordination service); each process decodes and feeds only its
addressable shard slots (ShardAssignment.local_slots +
jax.make_array_from_process_local_data), and cross-host psum reduces
return replicated results asserted against a host oracle inside each
worker (tests/multihost_worker.py).
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skip(
    reason="Multiprocess computations aren't implemented on the CPU "
    "backend: jax.distributed with gloo collectives over two CPU "
    "processes fails inside the framework, a pre-existing-at-seed "
    "limitation (not a regression) — run on a real multi-host TPU "
    "slice to exercise this path"
)
def test_two_process_mesh_query_correctness():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # skip axon TPU registration
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(worker))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    )
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_WORKER_{pid}_OK" in out, out[-4000:]
