"""Host roaring codec tests: container kinds, serialization round-trips,
op-log replay (modeled on the reference's roaring_test.go coverage —
SURVEY.md §4)."""

import numpy as np
import pytest

from pilosa_tpu.roaring import (
    OP_ADD,
    OP_REMOVE,
    RoaringBitmap,
    deserialize,
    serialize,
)
from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN, Container
from pilosa_tpu.roaring.format import encode_op, load, replay_ops


def make_ids(rng, kind):
    if kind == "sparse":
        return rng.choice(1 << 22, 300, replace=False).astype(np.uint64)
    if kind == "dense":
        base = rng.choice(1 << 18, 60_000, replace=False)
        return base.astype(np.uint64)
    if kind == "runs":
        out = []
        for start in rng.choice(1 << 22, 20, replace=False):
            out.extend(range(int(start), int(start) + int(rng.integers(100, 3000))))
        return np.array(sorted(set(out)), dtype=np.uint64)
    if kind == "mixed":
        a = make_ids(rng, "sparse")
        b = make_ids(rng, "runs")
        return np.unique(np.concatenate([a, b]))
    raise ValueError(kind)


@pytest.mark.parametrize("kind", ["sparse", "dense", "runs", "mixed"])
def test_roundtrip_ids(kind):
    rng = np.random.default_rng(hash(kind) % (1 << 31))
    ids = make_ids(rng, kind)
    b = RoaringBitmap.from_ids(ids)
    assert b.count() == ids.size
    np.testing.assert_array_equal(b.to_ids(), np.sort(ids))


def test_container_kind_selection():
    # few scattered values -> array
    assert Container.from_lows(np.array([1, 5, 900], np.uint16)).kind == ARRAY
    # long run -> run container
    assert Container.from_lows(np.arange(10_000, dtype=np.uint16)).kind == RUN
    # dense random -> bitmap
    rng = np.random.default_rng(0)
    lows = np.unique(rng.choice(65536, 30_000, replace=False)).astype(np.uint16)
    assert Container.from_lows(lows).kind == BITMAP


@pytest.mark.parametrize("kind", ["sparse", "dense", "runs", "mixed"])
def test_serialize_roundtrip(kind):
    rng = np.random.default_rng(hash(kind) % (1 << 30) + 1)
    ids = make_ids(rng, kind)
    b = RoaringBitmap.from_ids(ids)
    buf = serialize(b)
    b2, ops_at = deserialize(buf)
    assert ops_at == len(buf)
    assert b2 == b
    np.testing.assert_array_equal(b2.to_ids(), np.sort(ids))


def test_empty_bitmap():
    b = RoaringBitmap.from_ids([])
    assert b.count() == 0
    b2, _ = deserialize(serialize(b))
    assert b2.count() == 0
    assert b2.to_ids().size == 0


def test_add_remove_oracle():
    rng = np.random.default_rng(42)
    oracle = set()
    b = RoaringBitmap.from_ids([])
    for _ in range(20):
        batch = rng.choice(1 << 20, 500, replace=False).astype(np.uint64)
        if rng.random() < 0.6:
            expected_change = len(set(batch.tolist()) - oracle)
            assert b.add_ids(batch) == expected_change
            oracle |= set(batch.tolist())
        else:
            expected_change = len(set(batch.tolist()) & oracle)
            assert b.remove_ids(batch) == expected_change
            oracle -= set(batch.tolist())
        assert b.count() == len(oracle)
    np.testing.assert_array_equal(b.to_ids(), np.array(sorted(oracle), np.uint64))


def test_op_log_replay_and_torn_tail():
    base_ids = np.arange(0, 5000, 3, dtype=np.uint64)
    b = RoaringBitmap.from_ids(base_ids)
    buf = serialize(b)
    buf += encode_op(OP_ADD, [1, 2, 100_000])
    buf += encode_op(OP_REMOVE, [0, 3, 6])
    full, n_ops = load(buf)
    assert n_ops == 2
    expected = (set(base_ids.tolist()) | {1, 2, 100_000}) - {0, 3, 6}
    np.testing.assert_array_equal(full.to_ids(), np.array(sorted(expected), np.uint64))

    # torn final record: truncated mid-ids — must be ignored
    torn = buf + encode_op(OP_ADD, list(range(64)))[:-7]
    full2, n_ops2 = load(torn)
    assert n_ops2 == 2
    assert full2 == full

    # corrupt crc in the tail record — ignored as well
    bad = bytearray(buf + encode_op(OP_ADD, [7]))
    bad[-1] ^= 0xFF
    full3, n_ops3 = load(bytes(bad))
    assert n_ops3 == 2


def test_count_range():
    ids = np.array([0, 100, 65535, 65536, 70000, 200_000, (1 << 20) - 1], np.uint64)
    b = RoaringBitmap.from_ids(ids)
    assert b.count_range(0, 1 << 20) == len(ids)
    assert b.count_range(100, 65537) == 3
    assert b.count_range(65536, 65537) == 1
    assert b.count_range(5, 5) == 0
    assert b.count_range(200_001, 1 << 20) == 1


def test_dense_range_words_matches_pack():
    from pilosa_tpu.ops.packing import pack_bits, unpack_bits

    rng = np.random.default_rng(9)
    # ids within "row 3" of a fragment: [3*2^20, 4*2^20)
    row_base = 3 << 20
    ids = np.sort(rng.choice(1 << 20, 5000, replace=False)).astype(np.uint64)
    b = RoaringBitmap.from_ids(ids + np.uint64(row_base))
    words = b.dense_range_words32(row_base, row_base + (1 << 20))
    np.testing.assert_array_equal(words, pack_bits(ids, 1 << 20))
    np.testing.assert_array_equal(unpack_bits(words), ids)


def test_contains():
    b = RoaringBitmap.from_ids([5, 65536 * 3 + 2])
    assert 5 in b
    assert 65536 * 3 + 2 in b
    assert 6 not in b


class TestPilosaLayout:
    """Upstream (reference) roaring file layout interop — reconstructed
    from knowledge of pilosa roaring.go, confidence MED (SURVEY.md
    EVIDENCE STATUS): cookie 12348, descriptors, offsets, ops."""

    def test_roundtrip_all_kinds(self):
        from pilosa_tpu.roaring.format import (
            deserialize_pilosa,
            load_any,
            serialize_pilosa,
        )

        rng = np.random.default_rng(21)
        ids = np.concatenate([
            rng.choice(1 << 16, 500, replace=False),                # array
            (1 << 16) + rng.choice(1 << 16, 30000, replace=False),  # bitmap
            (5 << 16) + np.arange(2000),                            # run
        ]).astype(np.uint64)
        bm = RoaringBitmap.from_ids(ids)
        blob = serialize_pilosa(bm)
        # cookie sniffable
        import struct as _s
        assert _s.unpack_from("<I", blob, 0)[0] & 0xFFFF == 12348
        back, ops_at = deserialize_pilosa(blob)
        assert back == bm
        # load_any sniffs the layout
        sniffed, n_ops = load_any(blob)
        assert sniffed == bm and n_ops == 0

    def test_ops_replay_and_torn_tail(self):
        import struct as _s

        from pilosa_tpu.roaring.format import fnv1a32, load_any, serialize_pilosa

        bm = RoaringBitmap.from_ids(np.asarray([1, 2, 3], np.uint64))
        blob = serialize_pilosa(bm)

        def op(typ, value):
            head = _s.pack("<BQ", typ, value)
            return head + _s.pack("<I", fnv1a32(head))

        blob += op(0, 99) + op(1, 2) + op(0, 1 << 20)
        blob += b"\x00\x07"  # torn tail: ignored
        got, n_ops = load_any(blob)
        assert n_ops == 3
        assert got.to_ids().tolist() == [1, 3, 99, 1 << 20]

    def test_fnv1a32_known_vectors(self):
        # Published FNV-1a 32 test vectors (same hash Go's fnv.New32a uses).
        from pilosa_tpu.roaring.format import fnv1a32

        assert fnv1a32(b"") == 0x811C9DC5
        assert fnv1a32(b"a") == 0xE40C292C
        assert fnv1a32(b"foobar") == 0xBF9CF968

    def test_strict_import_rejects_bad_op_checksum(self):
        import struct as _s

        import pytest

        from pilosa_tpu.roaring.format import load_any, replay_pilosa_ops, serialize_pilosa

        bm = RoaringBitmap.from_ids(np.asarray([1], np.uint64))
        blob = serialize_pilosa(bm)
        # A full-size record with a wrong checksum: the import path must
        # refuse (silent data loss otherwise); crash recovery tolerates it.
        blob += _s.pack("<BQI", 0, 42, 0xDEADBEEF)
        with pytest.raises(ValueError, match="checksum mismatch"):
            load_any(blob)
        got, n_ops = load_any(blob, strict_ops=False)
        assert n_ops == 0 and got.to_ids().tolist() == [1]
        # replay_pilosa_ops default (crash-recovery) path also tolerates it
        bm2 = RoaringBitmap.from_ids(np.asarray([1], np.uint64))
        assert replay_pilosa_ops(bm2, blob, len(serialize_pilosa(bm))) == 0

    def test_import_roaring_accepts_upstream_layout(self, tmp_path):
        from pilosa_tpu.roaring.format import serialize_pilosa
        from pilosa_tpu.storage import Holder

        holder = Holder(str(tmp_path / "d")).open()
        f = holder.create_index("i").create_field("f")
        from pilosa_tpu.storage.view import VIEW_STANDARD

        frag = f.view(VIEW_STANDARD, create=True).fragment(0, create=True)
        bm = RoaringBitmap.from_ids(
            np.asarray([(2 << 20) + 1, (2 << 20) + 4], np.uint64)
        )
        changed = frag.import_roaring(serialize_pilosa(bm))
        assert changed == 2
        assert frag.row_words(2) is not None
        assert frag.contains(2, 1) and frag.contains(2, 4)
        holder.close()


class TestFormatStability:
    def test_serialize_golden_bytes(self):
        """On-disk format stability: the exact serialized bytes for a
        fixed bitmap must never change silently — files written by one
        build must open in the next (both the native layout and the
        upstream-pilosa layout; run/array/bitmap container mix)."""
        import hashlib

        from pilosa_tpu.roaring.format import serialize, serialize_pilosa

        ids = np.concatenate([
            np.asarray(
                [0, 1, 2, 100000, (2 << 20) + 5, (1 << 40) + 7], np.uint64
            ),
            # 5000 ids in one 2^16 range: forces a BITMAP container so the
            # dense writer path is pinned too (run + array + bitmap mix)
            (np.arange(5000, dtype=np.uint64) * 13) % 65536 + (3 << 16),
        ])
        bm = RoaringBitmap.from_ids(ids)
        from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN

        kinds = {bm.container(k).kind for k in bm.keys}
        assert kinds == {ARRAY, BITMAP, RUN}
        own = serialize(bm)
        up = serialize_pilosa(bm)
        assert hashlib.sha256(own).hexdigest() == (
            "45403260f0bdaaffcc1ee2bff7b23d9bb72e406be0ff326542718fa6b9d56a2e"
        ), "native layout changed — bump the format version instead"
        assert hashlib.sha256(up).hexdigest() == (
            "c86eb3f56769bb1305f59b5f68dea81990ca0e7992d7c137b47d9495974dda0c"
        ), "upstream-layout writer changed — verify against real pilosa files"
