"""Host roaring codec tests: container kinds, serialization round-trips,
op-log replay (modeled on the reference's roaring_test.go coverage —
SURVEY.md §4)."""

import numpy as np
import pytest

from pilosa_tpu.roaring import (
    OP_ADD,
    OP_REMOVE,
    RoaringBitmap,
    deserialize,
    serialize,
)
from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN, Container
from pilosa_tpu.roaring.format import encode_op, load, replay_ops


def make_ids(rng, kind):
    if kind == "sparse":
        return rng.choice(1 << 22, 300, replace=False).astype(np.uint64)
    if kind == "dense":
        base = rng.choice(1 << 18, 60_000, replace=False)
        return base.astype(np.uint64)
    if kind == "runs":
        out = []
        for start in rng.choice(1 << 22, 20, replace=False):
            out.extend(range(int(start), int(start) + int(rng.integers(100, 3000))))
        return np.array(sorted(set(out)), dtype=np.uint64)
    if kind == "mixed":
        a = make_ids(rng, "sparse")
        b = make_ids(rng, "runs")
        return np.unique(np.concatenate([a, b]))
    raise ValueError(kind)


@pytest.mark.parametrize("kind", ["sparse", "dense", "runs", "mixed"])
def test_roundtrip_ids(kind):
    rng = np.random.default_rng(hash(kind) % (1 << 31))
    ids = make_ids(rng, kind)
    b = RoaringBitmap.from_ids(ids)
    assert b.count() == ids.size
    np.testing.assert_array_equal(b.to_ids(), np.sort(ids))


def test_container_kind_selection():
    # few scattered values -> array
    assert Container.from_lows(np.array([1, 5, 900], np.uint16)).kind == ARRAY
    # long run -> run container
    assert Container.from_lows(np.arange(10_000, dtype=np.uint16)).kind == RUN
    # dense random -> bitmap
    rng = np.random.default_rng(0)
    lows = np.unique(rng.choice(65536, 30_000, replace=False)).astype(np.uint16)
    assert Container.from_lows(lows).kind == BITMAP


@pytest.mark.parametrize("kind", ["sparse", "dense", "runs", "mixed"])
def test_serialize_roundtrip(kind):
    rng = np.random.default_rng(hash(kind) % (1 << 30) + 1)
    ids = make_ids(rng, kind)
    b = RoaringBitmap.from_ids(ids)
    buf = serialize(b)
    b2, ops_at = deserialize(buf)
    assert ops_at == len(buf)
    assert b2 == b
    np.testing.assert_array_equal(b2.to_ids(), np.sort(ids))


def test_empty_bitmap():
    b = RoaringBitmap.from_ids([])
    assert b.count() == 0
    b2, _ = deserialize(serialize(b))
    assert b2.count() == 0
    assert b2.to_ids().size == 0


def test_add_remove_oracle():
    rng = np.random.default_rng(42)
    oracle = set()
    b = RoaringBitmap.from_ids([])
    for _ in range(20):
        batch = rng.choice(1 << 20, 500, replace=False).astype(np.uint64)
        if rng.random() < 0.6:
            expected_change = len(set(batch.tolist()) - oracle)
            assert b.add_ids(batch) == expected_change
            oracle |= set(batch.tolist())
        else:
            expected_change = len(set(batch.tolist()) & oracle)
            assert b.remove_ids(batch) == expected_change
            oracle -= set(batch.tolist())
        assert b.count() == len(oracle)
    np.testing.assert_array_equal(b.to_ids(), np.array(sorted(oracle), np.uint64))


def test_op_log_replay_and_torn_tail():
    base_ids = np.arange(0, 5000, 3, dtype=np.uint64)
    b = RoaringBitmap.from_ids(base_ids)
    buf = serialize(b)
    buf += encode_op(OP_ADD, [1, 2, 100_000])
    buf += encode_op(OP_REMOVE, [0, 3, 6])
    full, n_ops = load(buf)
    assert n_ops == 2
    expected = (set(base_ids.tolist()) | {1, 2, 100_000}) - {0, 3, 6}
    np.testing.assert_array_equal(full.to_ids(), np.array(sorted(expected), np.uint64))

    # torn final record: truncated mid-ids — must be ignored
    torn = buf + encode_op(OP_ADD, list(range(64)))[:-7]
    full2, n_ops2 = load(torn)
    assert n_ops2 == 2
    assert full2 == full

    # corrupt crc in the tail record — ignored as well
    bad = bytearray(buf + encode_op(OP_ADD, [7]))
    bad[-1] ^= 0xFF
    full3, n_ops3 = load(bytes(bad))
    assert n_ops3 == 2


def test_count_range():
    ids = np.array([0, 100, 65535, 65536, 70000, 200_000, (1 << 20) - 1], np.uint64)
    b = RoaringBitmap.from_ids(ids)
    assert b.count_range(0, 1 << 20) == len(ids)
    assert b.count_range(100, 65537) == 3
    assert b.count_range(65536, 65537) == 1
    assert b.count_range(5, 5) == 0
    assert b.count_range(200_001, 1 << 20) == 1


def test_dense_range_words_matches_pack():
    from pilosa_tpu.ops.packing import pack_bits, unpack_bits

    rng = np.random.default_rng(9)
    # ids within "row 3" of a fragment: [3*2^20, 4*2^20)
    row_base = 3 << 20
    ids = np.sort(rng.choice(1 << 20, 5000, replace=False)).astype(np.uint64)
    b = RoaringBitmap.from_ids(ids + np.uint64(row_base))
    words = b.dense_range_words32(row_base, row_base + (1 << 20))
    np.testing.assert_array_equal(words, pack_bits(ids, 1 << 20))
    np.testing.assert_array_equal(unpack_bits(words), ids)


def test_contains():
    b = RoaringBitmap.from_ids([5, 65536 * 3 + 2])
    assert 5 in b
    assert 65536 * 3 + 2 in b
    assert 6 not in b
