"""PQL parser tests: parse → AST golden comparisons incl. errors
(reference pql/pql_test.go — SURVEY.md §4)."""

import pytest

from pilosa_tpu.pql import Call, Condition, ParseError, parse


def test_row_simple():
    q = parse("Row(stargazer=1)")
    assert q.calls == [Call("Row", {"stargazer": 1})]


def test_nested_set_ops():
    q = parse("Count(Intersect(Row(a=1), Row(b=2)))")
    (count,) = q.calls
    assert count.name == "Count"
    (inter,) = count.children
    assert inter.name == "Intersect"
    assert inter.children == [Call("Row", {"a": 1}), Call("Row", {"b": 2})]


def test_v0_aliases():
    q = parse("SetBit(10, f=1) Bitmap(f=1) ClearBit(10, f=1) SetValue(10, v=7)")
    assert [c.name for c in q.calls] == ["Set", "Row", "Clear", "Set"]
    assert q.write_calls() == q.calls[:1] + q.calls[2:]


def test_set_with_positional_column():
    q = parse("Set(10, stargazer=44)")
    assert q.calls[0].args == {"_col": 10, "stargazer": 44}


def test_string_keys_and_escapes():
    q = parse("Set('col\\'key', f=\"row key\")")
    assert q.calls[0].args == {"_col": "col'key", "f": "row key"}


def test_topn_positional_field():
    q = parse("TopN(stargazer, n=5)")
    assert q.calls[0].args == {"_field": "stargazer", "n": 5}


def test_topn_with_filter_child():
    q = parse("TopN(lang, Row(stargazer=1), n=3)")
    c = q.calls[0]
    assert c.args["_field"] == "lang"
    assert c.children == [Call("Row", {"stargazer": 1})]


def test_conditions():
    q = parse("Range(fare > 10)")
    assert q.calls[0].args == {"fare": Condition(">", 10)}
    for op in ("<", "<=", ">", ">=", "==", "!="):
        q = parse(f"Range(fare {op} -3)")
        assert q.calls[0].args["fare"] == Condition(op, -3)


def test_between_condition():
    q = parse("Range(fare >< [5, 10])")
    assert q.calls[0].args == {"fare": Condition("><", [5, 10])}


def test_row_time_range_args():
    q = parse("Row(f=3, from='2019-01-01T00:00', to='2019-02-01T00:00')")
    assert q.calls[0].args == {
        "f": 3, "from": "2019-01-01T00:00", "to": "2019-02-01T00:00",
    }


def test_groupby():
    q = parse("GroupBy(Rows(a), Rows(b), limit=10, filter=Row(c=1))")
    c = q.calls[0]
    assert [ch.name for ch in c.children] == ["Rows", "Rows"]
    assert c.args["limit"] == 10
    assert c.args["filter"] == Call("Row", {"c": 1})


def test_sum_with_field_arg():
    q = parse('Sum(Row(a=1), field="fare")')
    c = q.calls[0]
    assert c.args == {"field": "fare"}
    assert c.children == [Call("Row", {"a": 1})]
    # bare identifier also accepted as value
    assert parse("Sum(field=fare)").calls[0].args == {"field": "fare"}


def test_bool_and_float_values():
    q = parse("Options(Row(f=1), excludeColumns=true) Range(fare > 1.5)")
    assert q.calls[0].args == {"excludeColumns": True}
    assert q.calls[1].args["fare"] == Condition(">", 1.5)


def test_multiple_calls_whitespace():
    q = parse("  Set(1, f=2)\n\tSet(3, f=4)  ")
    assert len(q.calls) == 2
    assert q.write_calls() == q.calls


def test_shift_and_not():
    q = parse("Shift(Row(f=1), n=2) Not(Row(f=1)) All()")
    assert q.calls[0].args == {"n": 2}
    assert q.calls[1].children == [Call("Row", {"f": 1})]
    assert q.calls[2] == Call("All")


def test_parse_errors():
    for bad in (
        "", "Row(", "Bogus(f=1)", "Row(f=)", "Row(f=1", "Row(f==)",
        "Set(1 2, f=1)", "Row('unterminated)",
    ):
        with pytest.raises(ParseError):
            parse(bad)


def test_duplicate_condition_arg_rejected():
    """Condition(count > 1, count < 5) would silently keep only the last
    condition (dict overwrite); the parser rejects it and points at ><."""
    with pytest.raises(ParseError, match="duplicate condition"):
        parse("GroupBy(Rows(f), having=Condition(count > 1, count < 5))")
    # ranges spell it with the between operator
    q = parse("GroupBy(Rows(f), having=Condition(count >< [2, 4]))")
    having = q.calls[0].args["having"]
    assert having.args["count"] == Condition("><", [2, 4])


def test_negative_and_list_values():
    q = parse("Range(fare >< [-10, -5]) Row(f=-1)")
    assert q.calls[0].args["fare"] == Condition("><", [-10, -5])
    assert q.calls[1].args == {"f": -1}
