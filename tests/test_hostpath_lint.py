"""Tier-1 wiring for scripts/check_hostpath_loops.py: the repo stays
clean, and the lint actually bites when a per-container loop sneaks
back into a kernel-consumer module (read-side kernels AND the write
path's merge-kernel consumers — the module list lives in the script
and is imported here so the two can't drift)."""

import importlib.util
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
SCRIPT = REPO / "scripts" / "check_hostpath_loops.py"

_spec = importlib.util.spec_from_file_location("check_hostpath_loops",
                                               SCRIPT)
_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_lint)
MODULES = _lint.MODULES


def _run(*args):
    return subprocess.run(
        [sys.executable, str(SCRIPT), *args],
        capture_output=True, text=True, timeout=60,
    )


def _clone_consumers(tmp_path):
    for rel in MODULES:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)


def test_repo_is_clean():
    res = _run()
    assert res.returncode == 0, res.stdout + res.stderr


def test_write_path_modules_are_covered():
    # the merge-kernel consumer surfaces cannot silently drop out of
    # the lint: routing, WAL replay, and the dispatcher's home module
    for rel in [
        "pilosa_tpu/storage/fragment.py",
        "pilosa_tpu/server/api.py",
        "pilosa_tpu/storage/wal.py",
        "pilosa_tpu/parallel/cluster_exec.py",
        "pilosa_tpu/roaring/bitmap.py",
    ]:
        assert rel in MODULES, rel


def test_lint_catches_reintroduced_container_loop(tmp_path):
    # clone the consumer set into a scratch root, then regress one file
    _clone_consumers(tmp_path)
    victim = tmp_path / "pilosa_tpu" / "storage" / "integrity.py"
    victim.write_text(victim.read_text() + (
        "\n\ndef _regressed_walk(bitmap):\n"
        "    out = []\n"
        "    for key in bitmap.keys:\n"
        "        out.append(bitmap.container(key).lows())\n"
        "    return out\n"
    ))
    res = _run(str(tmp_path))
    assert res.returncode == 1, res.stdout + res.stderr
    assert "integrity.py" in res.stdout
    assert "_regressed_walk" in res.stdout


def test_lint_catches_regressed_write_merge_loop(tmp_path):
    # the exact regression the write-path rewire retired: a
    # per-container merge loop beside the kernel dispatcher
    _clone_consumers(tmp_path)
    victim = tmp_path / "pilosa_tpu" / "roaring" / "bitmap.py"
    victim.write_text(victim.read_text() + (
        "\n\ndef _regressed_merge(bm, ids):\n"
        "    for key in sorted(bm._containers):\n"
        "        bm._containers[key] = bm._containers[key]\n"
        "    return 0\n"
    ))
    res = _run(str(tmp_path))
    assert res.returncode == 1, res.stdout + res.stderr
    assert "_regressed_merge" in res.stdout


def test_allowlist_is_pinned_not_wildcarded(tmp_path):
    # a loop in a NON-allowlisted function of fragment.py must fail
    # even though fragment.py has an allowlist entry
    _clone_consumers(tmp_path)
    victim = tmp_path / "pilosa_tpu" / "storage" / "fragment.py"
    victim.write_text(victim.read_text() + (
        "\n\ndef _other_walk(bm):\n"
        "    return [bm.container(k) for k in bm.keys]\n"
    ))
    res = _run(str(tmp_path))
    assert res.returncode == 1
    assert "_other_walk" in res.stdout
