"""Tier-1 wiring for scripts/check_hostpath_loops.py: the repo stays
clean, and the lint actually bites when a per-container loop sneaks
back into a kernel-consumer module."""

import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
SCRIPT = REPO / "scripts" / "check_hostpath_loops.py"


def _run(*args):
    return subprocess.run(
        [sys.executable, str(SCRIPT), *args],
        capture_output=True, text=True, timeout=60,
    )


def test_repo_is_clean():
    res = _run()
    assert res.returncode == 0, res.stdout + res.stderr


def test_lint_catches_reintroduced_container_loop(tmp_path):
    # clone the consumer set into a scratch root, then regress one file
    for rel in [
        "pilosa_tpu/storage/fragment.py",
        "pilosa_tpu/storage/integrity.py",
        "pilosa_tpu/parallel/scrub.py",
        "pilosa_tpu/parallel/cluster.py",
        "pilosa_tpu/cdc/tailer.py",
    ]:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    victim = tmp_path / "pilosa_tpu" / "storage" / "integrity.py"
    victim.write_text(victim.read_text() + (
        "\n\ndef _regressed_walk(bitmap):\n"
        "    out = []\n"
        "    for key in bitmap.keys:\n"
        "        out.append(bitmap.container(key).lows())\n"
        "    return out\n"
    ))
    res = _run(str(tmp_path))
    assert res.returncode == 1, res.stdout + res.stderr
    assert "integrity.py" in res.stdout
    assert "_regressed_walk" in res.stdout


def test_allowlist_is_pinned_not_wildcarded(tmp_path):
    # a loop in a NON-allowlisted function of fragment.py must fail
    # even though fragment.py has an allowlist entry
    for rel in [
        "pilosa_tpu/storage/fragment.py",
        "pilosa_tpu/storage/integrity.py",
        "pilosa_tpu/parallel/scrub.py",
        "pilosa_tpu/parallel/cluster.py",
        "pilosa_tpu/cdc/tailer.py",
    ]:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(REPO / rel, dst)
    victim = tmp_path / "pilosa_tpu" / "storage" / "fragment.py"
    victim.write_text(victim.read_text() + (
        "\n\ndef _other_walk(bm):\n"
        "    return [bm.container(k) for k in bm.keys]\n"
    ))
    res = _run(str(tmp_path))
    assert res.returncode == 1
    assert "_other_walk" in res.stdout
