"""Two REAL OS processes form a cluster via the CLI entry point.

The in-process `make_cluster` suites share a Python heap, so a whole
class of bugs (state accidentally shared through module globals, env
leakage, CLI flag plumbing) can't surface there. This boots two
`python -m pilosa_tpu server` subprocesses — the exact artifact an
operator runs — joins them over loopback HTTP, and drives writes,
distributed queries, a routed mutex import, and a restart-resume.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

from pilosa_tpu.shardwidth import SHARD_WIDTH


def req(method, url, body=None):
    data = (body if isinstance(body, (bytes, type(None)))
            else json.dumps(body).encode())
    r = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(r, timeout=30) as resp:
        return json.loads(resp.read() or b"{}")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_server(tmp_path, name, port, seed_port=None):
    # config rides the TOML file (exercising the config-file path);
    # bind/port/data-dir ride CLI flags (flags > file precedence)
    cfg = tmp_path / f"{name}.toml"
    seeds = (f'seeds = ["http://127.0.0.1:{seed_port}"]\n'
             if seed_port is not None else "")
    cfg.write_text(
        f'name = "{name}"\n'
        "anti-entropy-interval = 0.0\n"
        "heartbeat-interval = 0.0\n"
        + seeds
    )
    args = [
        sys.executable, "-m", "pilosa_tpu", "server",
        "--config", str(cfg),
        "--data-dir", str(tmp_path / name), "--bind", "127.0.0.1",
        "--port", str(port),
    ]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        args, env=os.environ.copy(), cwd=repo_root,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )
    base = f"http://127.0.0.1:{port}"
    for _ in range(120):
        if proc.poll() is not None:
            raise AssertionError(f"server {name} exited rc={proc.returncode}")
        try:
            req("GET", f"{base}/status")
            return proc, base
        except Exception:
            time.sleep(0.25)
    proc.terminate()
    raise AssertionError(f"server {name} never served /status")


def wait_members(base, want, timeout=20):
    """Poll /status until the membership set converges (join handling
    is asynchronous relative to the joiner's own /status coming up)."""
    deadline = time.time() + timeout
    seen = set()
    while time.time() < deadline:
        seen = {n["id"] for n in req("GET", f"{base}/status")["nodes"]}
        if seen == want:
            return
        time.sleep(0.2)
    raise AssertionError(f"{base}: members {seen} != {want}")




def terminate_all(procs):
    """Shared teardown: TERM everyone first, then reap (kill stragglers)."""
    for p in procs:
        if p is not None:
            p.terminate()
    for p in procs:
        if p is None:
            continue
        try:
            p.wait(15)
        except subprocess.TimeoutExpired:
            p.kill()


def test_two_process_cluster_end_to_end(tmp_path):
    p0 = p1 = None
    port0, port1 = free_port(), free_port()
    try:
        p0, b0 = spawn_server(tmp_path, "p0", port0)
        p1, b1 = spawn_server(tmp_path, "p1", port1, seed_port=port0)
        for b in (b0, b1):
            wait_members(b, {"p0", "p1"})

        req("POST", f"{b0}/index/i", {})
        req("POST", f"{b0}/index/i/field/f", {})
        req("POST", f"{b0}/index/i/field/m", {"options": {"type": "mutex"}})
        cols = [s * SHARD_WIDTH + 3 for s in range(6)]
        req("POST", f"{b0}/index/i/field/f/import",
            {"rows": [1] * len(cols), "columns": cols})
        # schema broadcast reached the peer process; queries fan out
        for b in (b1, b0):
            out = req("POST", f"{b}/index/i/query", b"Count(Row(f=1))")
            assert out == {"results": [6]}, b
        # routed mutex import through the PEER, then move the rows
        req("POST", f"{b1}/index/i/field/m/import",
            {"rows": [1] * len(cols), "columns": cols})
        req("POST", f"{b1}/index/i/field/m/import",
            {"rows": [2] * len(cols), "columns": cols})
        for b in (b0, b1):
            assert req("POST", f"{b}/index/i/query",
                       b"Count(Row(m=1))") == {"results": [0]}, b
            assert req("POST", f"{b}/index/i/query",
                       b"Count(Row(m=2))") == {"results": [6]}, b

        # keyed index across processes: keys allocate on the coordinator
        # and resolve from either node
        req("POST", f"{b0}/index/people", {"options": {"keys": True}})
        req("POST", f"{b0}/index/people/field/likes",
            {"options": {"keys": True}})
        req("POST", f"{b1}/index/people/query",
            b'Set("alice", likes="pizza")')
        req("POST", f"{b0}/index/people/query",
            b'Set("bob", likes="pizza")')
        req("POST", f"{b1}/index/people/query",
            b'Set("alice", likes="sushi")')
        for b in (b0, b1):
            out = req("POST", f"{b}/index/people/query",
                      b'Row(likes="pizza")')
            assert sorted(out["results"][0]["keys"]) == ["alice", "bob"], b
            out = req("POST", f"{b}/index/people/query",
                      b'Count(Row(likes="sushi"))')
            assert out == {"results": [1]}, b

        # restart the seed process: holder reopen = checkpoint resume,
        # and the restarted node must rejoin and serve
        p0.terminate()
        p0.wait(15)
        p0, b0 = spawn_server(tmp_path, "p0", port0, seed_port=port1)
        wait_members(b0, {"p0", "p1"})
        out = req("POST", f"{b0}/index/i/query", b"Count(Row(f=1))")
        assert out == {"results": [6]}
    finally:
        terminate_all([p0, p1])


def test_sigkill_durability_acked_writes_survive(tmp_path):
    """Hard-kill (SIGKILL) a server mid-workload: every ACKED write must
    survive the restart (op log is flushed per record before the HTTP
    response; recovery = snapshot + replay with torn tails dropped)."""
    p = None
    port = free_port()
    try:
        p, b = spawn_server(tmp_path, "d0", port)
        req("POST", f"{b}/index/i", {})
        req("POST", f"{b}/index/i/field/f", {})
        req("POST", f"{b}/index/i/field/v",
            {"options": {"type": "int", "min": 0, "max": 10000}})
        acked_bits = 0
        acked_vals = {}
        for batch in range(20):
            cols = [batch * 500 + k for k in range(100)]
            out = req("POST", f"{b}/index/i/field/f/import",
                      {"rows": [1] * len(cols), "columns": cols})
            acked_bits += out["changed"]
            out = req("POST", f"{b}/index/i/field/v/import-value",
                      {"columns": cols[:10], "values": [batch] * 10})
            for c in cols[:10]:
                acked_vals[c] = batch
        p.kill()  # SIGKILL: no close(), no snapshot, no cache save
        p.wait(15)
        p, b = spawn_server(tmp_path, "d0", port)
        out = req("POST", f"{b}/index/i/query", b"Count(Row(f=1))")
        assert out == {"results": [acked_bits]}
        out = req("POST", f"{b}/index/i/query", b'Sum(field="v")')
        assert out["results"][0] == {
            "value": sum(acked_vals.values()), "count": len(acked_vals),
        }
        # and the reopened store keeps serving writes
        out = req("POST", f"{b}/index/i/query", b"Set(999999, f=1)")
        assert out == {"results": [True]}
    finally:
        terminate_all([p])


def test_third_process_joins_resize_and_cleanup(tmp_path):
    """A third OS process joins a live 2-process cluster: the resize
    moves its owned shards' data across real process boundaries, the
    post-resize cleanup leaves each shard on exactly its owner, and
    cluster-wide queries stay exact from every process throughout."""
    procs = []
    try:
        port0, port1, port2 = free_port(), free_port(), free_port()
        p0, b0 = spawn_server(tmp_path, "q0", port0)
        procs.append(p0)
        p1, b1 = spawn_server(tmp_path, "q1", port1, seed_port=port0)
        procs.append(p1)
        for b in (b0, b1):
            wait_members(b, {"q0", "q1"})
        req("POST", f"{b0}/index/i", {})
        req("POST", f"{b0}/index/i/field/f", {})
        cols = [s * SHARD_WIDTH + c for s in range(8) for c in (3, 9)]
        req("POST", f"{b0}/index/i/field/f/import",
            {"rows": [1] * len(cols), "columns": cols})
        assert req("POST", f"{b0}/index/i/query",
                   b"Count(Row(f=1))") == {"results": [16]}

        p2, b2 = spawn_server(tmp_path, "q2", port2, seed_port=port0)
        procs.append(p2)
        for b in (b0, b1, b2):
            wait_members(b, {"q0", "q1", "q2"})
        # resize completes: the joiner drains to NORMAL and every node
        # answers the full count (including the joiner's moved shards)
        deadline = time.time() + 30
        while time.time() < deadline:
            st = req("GET", f"{b2}/status")
            if st["state"] == "NORMAL":
                break
            time.sleep(0.25)
        assert st["state"] == "NORMAL", st
        for b in (b0, b1, b2):
            out = req("POST", f"{b}/index/i/query", b"Count(Row(f=1))")
            assert out == {"results": [16]}, b
        # writes through the NEW process land and are visible everywhere
        req("POST", f"{b2}/index/i/query",
            "Set({}, f=2)".format(3 * SHARD_WIDTH + 77).encode())
        for b in (b0, b1, b2):
            assert req("POST", f"{b}/index/i/query",
                       b"Count(Row(f=2))") == {"results": [1]}, b
        # post-resize cleanup (async): eventually no shard's fragment
        # file exists on more than replica_n=1 processes
        deadline = time.time() + 30
        while time.time() < deadline:
            over = []
            for s in range(8):
                holders = [
                    n for n in ("q0", "q1", "q2")
                    if (tmp_path / n / "i" / "f" / "views" / "standard"
                        / "fragments" / str(s)).exists()
                ]
                if len(holders) > 1:
                    over.append((s, holders))
            if not over:
                break
            time.sleep(0.5)
        assert not over, over
        # and the data still fully reachable after cleanup
        for b in (b0, b1, b2):
            assert req("POST", f"{b}/index/i/query",
                       b"Count(Row(f=1))") == {"results": [16]}, b
    finally:
        terminate_all(procs)
