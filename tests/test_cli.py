"""CLI tests: import/export/inspect/check against a data dir in-process
(reference ctl/ coverage — SURVEY.md §2 #29)."""

import io
import json
import sys

import pytest

from pilosa_tpu.cli import main


def run_cli(argv, stdin: str | None = None, capsys=None):
    if stdin is not None:
        old = sys.stdin
        sys.stdin = io.StringIO(stdin)
        try:
            return main(argv)
        finally:
            sys.stdin = old
    return main(argv)


def test_import_export_roundtrip(tmp_path, capsys):
    data_dir = str(tmp_path / "data")
    csv = tmp_path / "bits.csv"
    csv.write_text("1,10\n1,20\n2,10\n")
    rc = main(["import", "-i", "i", "-f", "f", "-d", data_dir, "--create", str(csv)])
    assert rc == 0
    assert "3 bits changed" in capsys.readouterr().out

    rc = main(["export", "-i", "i", "-f", "f", "-d", data_dir])
    assert rc == 0
    assert capsys.readouterr().out.splitlines() == ["1,10", "1,20", "2,10"]


def test_import_values_and_check_inspect(tmp_path, capsys):
    data_dir = str(tmp_path / "data")
    csv = tmp_path / "vals.csv"
    csv.write_text("0,5\n1,42\n")
    rc = main(["import", "-i", "taxi", "-f", "fare", "-d", data_dir,
               "--create", "--values", "--min", "0", "--max", "100", str(csv)])
    assert rc == 0

    rc = main(["inspect", "-d", data_dir])
    out = capsys.readouterr().out
    assert rc == 0 and "taxi/fare/bsig_fare/0" in out

    rc = main(["check", "-d", data_dir])
    out = capsys.readouterr().out
    assert rc == 0 and "ok:" in out


def test_import_clear(tmp_path, capsys):
    data_dir = str(tmp_path / "data")
    csv = tmp_path / "bits.csv"
    csv.write_text("1,10\n")
    main(["import", "-i", "i", "-f", "f", "-d", data_dir, "--create", str(csv)])
    capsys.readouterr()
    main(["import", "-i", "i", "-f", "f", "-d", data_dir, "--clear", str(csv)])
    capsys.readouterr()
    main(["export", "-i", "i", "-f", "f", "-d", data_dir])
    assert capsys.readouterr().out == ""


def test_config_commands(capsys):
    rc = main(["generate-config"])
    out = capsys.readouterr().out
    assert rc == 0 and 'data-dir' in out

    rc = main(["config"])
    cfg = json.loads(capsys.readouterr().out)
    assert rc == 0 and cfg["port"] == 10101


def test_config_env_precedence(tmp_path, capsys, monkeypatch):
    toml = tmp_path / "c.toml"
    toml.write_text('port = 7777\nbind = "0.0.0.0"\n')
    monkeypatch.setenv("PILOSA_TPU_PORT", "8888")
    rc = main(["config", "-c", str(toml)])
    cfg = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert cfg["port"] == 8888  # env beats file
    assert cfg["bind"] == "0.0.0.0"  # file beats default


def test_version(capsys):
    assert main(["version"]) == 0
    assert capsys.readouterr().out.strip()
