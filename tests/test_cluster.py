"""In-process multi-node cluster tests.

The analog of the reference's key fixture test.MustRunCluster (SURVEY.md
§4): boots n real Servers in ONE process, each with its own temp data dir
and real HTTP listener on an ephemeral localhost port. No mocks — remote
mapReduce, schema broadcast, routed writes, replication, and anti-entropy
all run over loopback HTTP.
"""

import json
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.server import Server, ServerConfig
from pilosa_tpu.shardwidth import SHARD_WIDTH


# one make_cluster for every cluster suite (node names/dirs/ticker-off
# semantics identical; keeping a private copy here meant every new
# ServerConfig knob needed a synchronized two-file edit)
from cluster_helpers import make_cluster  # noqa: E402


def req(method, url, body=None, content_type="application/json"):
    data = body if isinstance(body, (bytes, type(None))) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        r.add_header("Content-Type", content_type)
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read() or b"{}")


@pytest.fixture
def cluster3(tmp_path):
    servers = make_cluster(tmp_path, 3)
    yield servers
    for s in servers:
        s.close()


def uri(s: Server) -> str:
    return f"http://localhost:{s.port}"


def _resize_pair(tmp_path, servers):
    """Shared resize-test fixture: schema on node 0, one fragment on the
    acting coordinator so the PEER is the owner that must fetch it.
    Returns (coord, peer)."""
    req("POST", f"{uri(servers[0])}/index/i", {})
    req("POST", f"{uri(servers[0])}/index/i/field/f", {})
    coord = next(s for s in servers if s.api.cluster.is_acting_coordinator)
    peer = next(s for s in servers if s is not coord)
    fc = coord.holder.index("i").field("f")
    fragc = fc.view("standard", create=True).fragment(3, create=True)
    fragc.bulk_import(np.asarray([2], np.uint64), np.asarray([5], np.uint64))
    return coord, peer


class TestMembership:
    def test_all_nodes_see_each_other(self, cluster3):
        for s in cluster3:
            st = req("GET", f"{uri(s)}/status")
            assert {n["id"] for n in st["nodes"]} == {"n0", "n1", "n2"}
            assert st["state"] == "NORMAL"
        coords = {
            next(n["id"] for n in req("GET", f"{uri(s)}/status")["nodes"]
                 if n["isCoordinator"])
            for s in cluster3
        }
        assert len(coords) == 1  # everyone agrees on the coordinator

    def test_concurrent_joins_relay_membership(self, cluster3):
        """Two joiners racing through ONE seed each adopt the seed's
        /status member list as of THEIR join and announce only to those
        nodes — so neither ever learns the other, and each serves its
        own asymmetric ring (reads through one route around data the
        other holds: indistinguishable from lost acked writes at the
        edge). The node-join handler must gossip a first-seen join both
        ways; this pins that relay."""
        import time

        n0, n1, n2 = (s.api.cluster for s in cluster3)
        uris = {c.local.id: c.local.uri for c in (n0, n1, n2)}
        # hand-craft the race end-state: n1 joined first (seed+n1 know
        # each other), n2 fetched the seed's status BEFORE n1's announce
        # landed (knows the seed only), n2's own announce still in flight
        for c, drop in ((n0, "n2"), (n1, "n2"), (n2, "n1")):
            with c._lock:
                c.nodes.pop(drop, None)
                c._note_membership_changed_locked()
        # ... and now n2's announce arrives at the seed
        n0.handle_message(
            {"type": "node-join", "id": "n2", "uri": uris["n2"]})
        want = {"n0", "n1", "n2"}
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(set(c.nodes) == want for c in (n0, n1, n2)):
                break
            time.sleep(0.05)
        assert set(n1.nodes) == want  # the relay told the earlier joiner
        assert set(n2.nodes) == want  # ...and the new joiner about it
        assert set(n0.nodes) == want

    def test_schema_broadcast(self, cluster3):
        req("POST", f"{uri(cluster3[1])}/index/repos", {})
        req("POST", f"{uri(cluster3[1])}/index/repos/field/stargazer", {})
        for s in cluster3:
            schema = req("GET", f"{uri(s)}/schema")
            assert schema["indexes"][0]["name"] == "repos"
            assert schema["indexes"][0]["fields"][0]["name"] == "stargazer"

    def test_recalculate_caches_broadcasts(self, cluster3):
        """POST /recalculate-caches to ONE node repairs drifted TopN
        caches on EVERY node (reference api.RecalculateCaches: SendSync
        then local recount)."""
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/f", {})
        for shard in range(6):  # bits spread across all three nodes
            cols = [shard * SHARD_WIDTH + c for c in range(4)]
            req("POST", f"{uri(cluster3[shard % 3])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
        # drift every node's caches for its local fragments of field f
        drifted = []
        for s in cluster3:
            for view in s.holder.indexes["i"].fields["f"].views.values():
                for frag in view.fragments.values():
                    frag.row_cache.bulk_add(1, 12345)
                    frag.row_cache.bulk_add(77, 9)  # phantom
                    drifted.append(frag)
        assert drifted
        r = urllib.request.Request(
            f"{uri(cluster3[2])}/recalculate-caches", data=b"",
            method="POST",
        )
        with urllib.request.urlopen(r) as resp:
            assert resp.status == 204
        # 204 = queued: every node recounts in a background worker so
        # message delivery/heartbeats never stall on the scan (ADVICE
        # r5); join each node's worker before asserting
        for s in cluster3:
            t = s.api._recalc_thread
            if t is not None:
                t.join(timeout=30)
        for frag in drifted:
            assert frag.row_cache.get(77) is None, frag.frag_id
            c = frag.row_cache.get(1)
            assert c is None or c != 12345, frag.frag_id


class TestDistributedQueries:
    def seed_data(self, cluster3):
        """Write bits spanning 6 shards through different nodes."""
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/f", {})
        oracle = {}
        for shard in range(6):
            cols = [shard * SHARD_WIDTH + c for c in range(10 * (shard + 1))]
            node = cluster3[shard % 3]
            body = {"rows": [1] * len(cols), "columns": cols}
            req("POST", f"{uri(node)}/index/i/field/f/import", body)
            oracle[shard] = cols
        return oracle

    def test_writes_route_and_queries_fan_out(self, cluster3):
        oracle = self.seed_data(cluster3)
        total = sum(len(v) for v in oracle.values())
        for s in cluster3:  # every node sees the global count
            out = req("POST", f"{uri(s)}/index/i/query", b"Count(Row(f=1))")
            assert out["results"] == [total]

    def test_row_union_across_nodes(self, cluster3):
        oracle = self.seed_data(cluster3)
        out = req("POST", f"{uri(cluster3[2])}/index/i/query", b"Row(f=1)")
        expect = sorted(c for cols in oracle.values() for c in cols)
        assert out["results"][0]["columns"] == expect

    def test_set_via_any_node(self, cluster3):
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/f", {})
        # single-bit Sets through node 2, columns across many shards
        for shard in range(5):
            col = shard * SHARD_WIDTH + 7
            out = req("POST", f"{uri(cluster3[2])}/index/i/query",
                      f"Set({col}, f=9)".encode())
            assert out["results"] == [True]
        out = req("POST", f"{uri(cluster3[0])}/index/i/query", b"Count(Row(f=9))")
        assert out["results"] == [5]

    def test_topn_two_phase_across_nodes(self, cluster3):
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/f", {})
        # row r gets 10*r bits spread over shards owned by different nodes
        for row, n_bits in [(1, 10), (2, 40), (3, 25)]:
            cols = [
                (i % 6) * SHARD_WIDTH + (row * 1000 + i) for i in range(n_bits)
            ]
            req("POST", f"{uri(cluster3[0])}/index/i/field/f/import",
                {"rows": [row] * len(cols), "columns": cols})
        out = req("POST", f"{uri(cluster3[1])}/index/i/query", b"TopN(f, n=2)")
        assert out["results"][0] == [
            {"id": 2, "count": 40}, {"id": 3, "count": 25},
        ]

    def test_topn_threshold_applies_after_cross_node_merge(self, cluster3):
        """threshold= filters GLOBAL counts: rows whose per-node partial
        counts all sit below the floor but whose merged count qualifies
        must survive (the mapped sub-queries carry no threshold)."""
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/f", {})
        # row 2: 5 bits in each of 6 shards (owned by different nodes)
        # → every per-node partial ≤ 10, global = 30
        for row, per_shard in [(1, 1), (2, 5)]:
            cols = [
                s * SHARD_WIDTH + row * 100 + i
                for s in range(6) for i in range(per_shard)
            ]
            req("POST", f"{uri(cluster3[0])}/index/i/field/f/import",
                {"rows": [row] * len(cols), "columns": cols})
        out = req("POST", f"{uri(cluster3[1])}/index/i/query",
                  b"TopN(f, n=10, threshold=20)")
        assert out["results"][0] == [{"id": 2, "count": 30}]

    def test_groupby_having_applies_after_cross_node_merge(self, cluster3):
        """having=Condition(count > N) filters MERGED group counts; a
        per-node filter would wrongly drop groups whose partials are
        individually under the floor."""
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/a", {})
        for shard in range(6):
            base = shard * SHARD_WIDTH
            # row 1: 2 bits/shard (global 12); row 2: 1 bit/shard (global 6)
            req("POST", f"{uri(cluster3[0])}/index/i/field/a/import",
                {"rows": [1, 1, 2], "columns": [base, base + 1, base + 2]})
        out = req("POST", f"{uri(cluster3[1])}/index/i/query",
                  b"GroupBy(Rows(a), having=Condition(count > 8))")
        assert out["results"][0] == [
            {"group": [{"field": "a", "rowID": 1}], "count": 12}
        ]

    def test_options_shards_no_double_count_with_replication(self, tmp_path):
        """Options(shards=) on a replicated cluster: a remote sub-query
        must evaluate only its ASSIGNED slice of the user's shard set —
        overriding the assignment with the full user set makes every
        replica evaluate shards it holds as a SECONDARY too, double-
        counting them in the merge (3 nodes, replicaN=2: remote groups
        overlap through replication)."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            n_shards = 8
            cols = [s * SHARD_WIDTH + 1 for s in range(n_shards)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            all_shards = list(range(n_shards))
            pql = f"Options(Count(Row(f=1)), shards={all_shards})".encode()
            for s in servers:  # every coordinator sees the exact count
                out = req("POST", f"{uri(s)}/index/i/query", pql)
                assert out["results"] == [n_shards], (s.config.name, out)
            out = req("POST", f"{uri(servers[0])}/index/i/query",
                      b"Options(Count(Row(f=1)), shards=[0, 3, 5])")
            assert out["results"] == [3], out
            # a request-level ?shards= restriction INTERSECTS the
            # Options(shards=) set (never widened), same as single-node
            out = req("POST",
                      f"{uri(servers[0])}/index/i/query?shards=0,1",
                      f"Options(Count(Row(f=1)), shards={all_shards})"
                      .encode())
            assert out["results"] == [2], out
        finally:
            for s in servers:
                s.close()

    def test_includes_column_across_nodes(self, cluster3):
        """IncludesColumn routes to the column's shard owner; it honors
        Options(shards=) restrictions and keyed columns cluster-wide."""
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/f", {})
        cols = [s * SHARD_WIDTH + 3 for s in range(6)]
        req("POST", f"{uri(cluster3[0])}/index/i/field/f/import",
            {"rows": [1] * len(cols), "columns": cols})
        target = 4 * SHARD_WIDTH + 3  # shard 4, wherever it lives
        for s in cluster3:  # answer identical from every coordinator
            out = req("POST", f"{uri(s)}/index/i/query",
                      f"IncludesColumn(Row(f=1), column={target})".encode())
            assert out["results"] == [True], s.config.name
        out = req("POST", f"{uri(cluster3[1])}/index/i/query",
                  f"Options(IncludesColumn(Row(f=1), column={target}), "
                  f"shards=[0, 1])".encode())
        assert out["results"] == [False]
        out = req("POST", f"{uri(cluster3[1])}/index/i/query",
                  f"IncludesColumn(Row(f=1), column={target + 1})".encode())
        assert out["results"] == [False]

    def test_bsi_sum_across_nodes(self, cluster3):
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/v",
            {"options": {"type": "int", "min": 0, "max": 1000}})
        cols = [s * SHARD_WIDTH for s in range(6)]
        vals = [10, 20, 30, 40, 50, 60]
        req("POST", f"{uri(cluster3[1])}/index/i/field/v/import-value",
            {"columns": cols, "values": vals})
        out = req("POST", f"{uri(cluster3[2])}/index/i/query", b'Sum(field="v")')
        assert out["results"][0] == {"value": 210, "count": 6}
        out = req("POST", f"{uri(cluster3[0])}/index/i/query", b"Count(Range(v > 25))")
        assert out["results"] == [4]

    def test_groupby_across_nodes(self, cluster3):
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/a", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/b", {})
        for shard in range(4):
            base = shard * SHARD_WIDTH
            req("POST", f"{uri(cluster3[0])}/index/i/field/a/import",
                {"rows": [1] * 6, "columns": [base + c for c in range(6)]})
            req("POST", f"{uri(cluster3[0])}/index/i/field/b/import",
                {"rows": [7] * 3, "columns": [base + c for c in range(0, 6, 2)]})
        out = req("POST", f"{uri(cluster3[1])}/index/i/query",
                  b"GroupBy(Rows(a), Rows(b))")
        assert out["results"][0] == [
            {"group": [{"field": "a", "rowID": 1}, {"field": "b", "rowID": 7}],
             "count": 12}
        ]


    def test_groupby_aggregate_sum_across_nodes(self, cluster3):
        req("POST", f"{uri(cluster3[0])}/index/i", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/a", {})
        req("POST", f"{uri(cluster3[0])}/index/i/field/amt",
            {"options": {"type": "int", "min": 0, "max": 1000}})
        cols, vals = [], []
        for shard in range(4):
            base = shard * SHARD_WIDTH
            req("POST", f"{uri(cluster3[0])}/index/i/field/a/import",
                {"rows": [1, 1], "columns": [base, base + 1]})
            cols += [base, base + 1]
            vals += [10 * (shard + 1), 1]
        req("POST", f"{uri(cluster3[1])}/index/i/field/amt/import-value",
            {"columns": cols, "values": vals})
        out = req("POST", f"{uri(cluster3[2])}/index/i/query",
                  b'GroupBy(Rows(a), aggregate=Sum(field="amt"))')
        (g,) = out["results"][0]
        assert g["group"] == [{"field": "a", "rowID": 1}]
        assert g["count"] == 8
        assert g["sum"] == sum(vals)


class TestReplication:
    def test_replica_writes_land_on_two_nodes(self, tmp_path):
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 1 for s in range(4)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            # each shard's fragment must exist on exactly replica_n holders
            for shard in range(4):
                holders_with = sum(
                    1 for s in servers
                    if (f := s.holder.index("i").field("f").view("standard"))
                    and f.fragment(shard) is not None
                    and f.fragment(shard).contains(1, 1)
                )
                assert holders_with == 2, f"shard {shard}"
            # queries still see each shard once
            out = req("POST", f"{uri(servers[1])}/index/i/query", b"Count(Row(f=1))")
            assert out["results"] == [4]
        finally:
            for s in servers:
                s.close()

    def test_anti_entropy_repairs_diverged_replica(self, tmp_path):
        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            req("POST", f"{uri(servers[0])}/index/i/query", b"Set(1, f=1)")
            # diverge: write a bit directly into node0's holder only
            frag = (servers[0].holder.index("i").field("f")
                    .view("standard").fragment(0, create=True))
            frag.set_bit(1, 999)
            frag1 = (servers[1].holder.index("i").field("f")
                     .view("standard").fragment(0))
            assert not frag1.contains(1, 999)
            # node1 pulls the missing bits during its sync pass
            repaired = servers[1].api.cluster.sync_holder()
            assert repaired["bits"] >= 1
            assert frag1.contains(1, 999)
        finally:
            for s in servers:
                s.close()


class TestJoinResize:
    def test_new_node_fetches_owned_fragments(self, tmp_path):
        servers = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 3 for s in range(16)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            # join a second node
            servers += make_cluster(tmp_path / "late", 0)  # no-op, keep shape
            cfg = ServerConfig(
                data_dir=str(tmp_path / "node_late"), port=0, name="n9",
                seeds=[uri(servers[0])], anti_entropy_interval=0,
                heartbeat_interval=0, use_mesh=False,
            )
            late = Server(cfg).open()
            servers.append(late)
            # the join-time fetch runs as a background job; wait for it
            assert late.api.cluster.wait_until_normal(30)
            # membership propagated
            st = req("GET", f"{uri(servers[0])}/status")
            assert {n["id"] for n in st["nodes"]} == {"n0", "n9"}
            # schema adopted
            assert late.holder.index("i") is not None
            # the late node now owns some shards and must have their data
            owned = [s for s in range(16)
                     if late.api.cluster.owns_shard("i", s)]
            assert owned, "hash ring should give the new node some shards"
            view = late.holder.index("i").field("f").view("standard")
            for shard in owned:
                frag = view.fragment(shard) if view else None
                assert frag is not None and frag.contains(1, 3), f"shard {shard}"
            # cluster-wide queries remain correct from either node
            out = req("POST", f"{uri(late)}/index/i/query", b"Count(Row(f=1))")
            assert out["results"] == [16]
        finally:
            for s in servers:
                s.close()


    def test_self_join_fetch_falls_back_to_replica_and_dedupes(
        self, tmp_path, monkeypatch
    ):
        """replicaN=2 self-join: the inventory lists each owned fragment
        ONCE (not once per replica), and when the chosen source errors on
        the data fetch the replica fallback supplies the fragment instead
        of silently losing it until anti-entropy."""
        from pilosa_tpu.parallel.client import ClientError, InternalClient

        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 3 for s in range(8)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            # with replicaN=2 and two nodes, BOTH peers hold every
            # fragment; break one peer's data endpoint for everyone and
            # record every fetch attempt
            broken_uri = uri(servers[1])
            real_fd = InternalClient.fragment_data
            fetched: list[tuple] = []

            def flaky_fragment_data(client, node_uri, index, field, view,
                                    shard, *a, **k):
                fetched.append((node_uri, field, view, shard))
                if node_uri == broken_uri:
                    raise ClientError(f"injected failure for {node_uri}")
                return real_fd(client, node_uri, index, field, view, shard,
                               *a, **k)

            monkeypatch.setattr(
                InternalClient, "fragment_data", flaky_fragment_data
            )
            cfg = ServerConfig(
                data_dir=str(tmp_path / "node_late"), port=0, name="n9",
                seeds=[uri(servers[0])], anti_entropy_interval=0,
                heartbeat_interval=0, use_mesh=False, replica_n=2,
            )
            late = Server(cfg).open()
            servers.append(late)
            assert late.api.cluster.wait_until_normal(30)
            # every owned shard's data landed despite the broken peer
            owned = [s for s in range(8)
                     if late.api.cluster.owns_shard("i", s)]
            assert owned
            view = late.holder.index("i").field("f").view("standard")
            for shard in owned:
                frag = view.fragment(shard)
                assert frag is not None and frag.contains(1, 3), f"shard {shard}"
            # dedup: the inventory lists each fragment once (NOT once per
            # replica), so no key is fetched more than twice — twice only
            # when the joiner's inventory fetch and the coordinator's
            # instruction job overlap, a DELIBERATE redundancy (each path
            # covers the other's failure modes; the union is idempotent)
            ok = [f for f in fetched if f[0] != broken_uri]
            assert ok
            from collections import Counter
            worst = Counter(ok).most_common(1)[0]
            assert worst[1] <= 2, worst
        finally:
            for s in servers:
                s.close()


class TestFailureHandling:
    def test_query_survives_replica_node_death(self, tmp_path):
        """replicaN=2: killing one node must not lose query coverage —
        routing falls back to the surviving replica (reference: memberlist
        dead event -> DEGRADED, reads served from remaining owners)."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 7 for s in range(6)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            out = req("POST", f"{uri(servers[0])}/index/i/query", b"Count(Row(f=1))")
            assert out["results"] == [6]

            victim = servers.pop(2)
            victim.close()
            # survivors notice on their next heartbeat pass
            for s in servers:
                s.api.cluster.heartbeat()
                states = {n.id: n.state for n in s.api.cluster.nodes.values()}
                assert states["n2"] == "DEGRADED", states

            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query", b"Count(Row(f=1))")
                assert out["results"] == [6]
                out = req("POST", f"{uri(s)}/index/i/query", b"Row(f=1)")
                assert out["results"][0]["columns"] == cols
        finally:
            for s in servers:
                s.close()

    def test_node_restart_recovers_data_and_membership(self, tmp_path):
        """Kill + restart on the same data dir: fragments reload from the
        roaring files + op logs (checkpoint/resume == holder.Open,
        SURVEY.md §5.4) and the node rejoins the cluster."""
        servers = make_cluster(tmp_path, 2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 1 for s in range(4)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            # unsnapshotted single-bit writes must also survive (op log)
            req("POST", f"{uri(servers[0])}/index/i/query", b"Set(123, f=9)")

            victim = servers.pop(1)
            victim_dir = victim.config.data_dir
            victim.close()
            servers[0].api.cluster.heartbeat()

            reborn = Server(ServerConfig(
                data_dir=victim_dir, port=0, name="n1",
                seeds=[uri(servers[0])], anti_entropy_interval=0,
                heartbeat_interval=0, use_mesh=False,
            )).open()
            servers.append(reborn)
            assert reborn.api.cluster.wait_until_normal(30)
            servers[0].api.cluster.heartbeat()
            st = req("GET", f"{uri(servers[0])}/status")
            assert {n["id"]: n["state"] for n in st["nodes"]} == {
                "n0": "NORMAL", "n1": "NORMAL"}

            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query", b"Count(Row(f=1))")
                assert out["results"] == [4]
                out = req("POST", f"{uri(s)}/index/i/query", b"Row(f=9)")
                assert out["results"][0]["columns"] == [123]
        finally:
            for s in servers:
                s.close()

    def test_rejoining_node_catches_up_before_serving(self, tmp_path):
        """replicaN=2: writes that landed on the surviving replica during
        a node's outage must be visible the moment the restarted node
        reaches NORMAL — the self-join gate block-diffs held (stale)
        fragments before releasing, not just fetching missing ones."""
        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 1 for s in range(4)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})

            victim = servers.pop(1)
            victim_dir = victim.config.data_dir
            victim.close()
            from pilosa_tpu.parallel.cluster import DEAD_HEARTBEATS

            for _ in range(DEAD_HEARTBEATS):
                servers[0].api.cluster.heartbeat()
            # outage-window writes: same row, new columns — the victim's
            # on-disk fragments are now non-empty AND stale
            stale_cols = [s * SHARD_WIDTH + 2 for s in range(4)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(stale_cols), "columns": stale_cols})

            reborn = Server(ServerConfig(
                data_dir=victim_dir, port=0, name="n1",
                seeds=[uri(servers[0])], anti_entropy_interval=0,
                heartbeat_interval=0, use_mesh=False, replica_n=2,
            )).open()
            servers.append(reborn)
            assert reborn.api.cluster.wait_until_normal(30)
            # the reborn node's LOCAL fragments carry the outage writes
            # (no cross-node query help: ask its holder directly)
            view = reborn.holder.index("i").field("f").view("standard")
            for shard in range(4):
                if not reborn.api.cluster.owns_shard("i", shard):
                    continue
                frag = view.fragment(shard)
                assert frag is not None and frag.contains(1, 2), (
                    f"shard {shard} missing outage-window write"
                )
        finally:
            for s in servers:
                s.close()


class TestResizeAndReReplication:
    def test_heartbeat_death_triggers_auto_rereplication(self, tmp_path):
        """Kill a node; after DEAD_HEARTBEATS failed probes the acting
        coordinator removes it and drives coordinator-computed resize
        instructions until every shard is back at full replica count
        (VERDICT r1 #7: no manual join or anti-entropy pass needed)."""
        from pilosa_tpu.parallel.cluster import DEAD_HEARTBEATS

        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 11 for s in range(8)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})

            victim = servers.pop(2)
            victim.close()
            for _ in range(DEAD_HEARTBEATS):
                for s in servers:
                    s.api.cluster.heartbeat()

            # membership converged: the dead node is gone everywhere
            for s in servers:
                assert set(s.api.cluster.nodes) == {"n0", "n1"}, (
                    s.api.cluster.nodes)
                assert s.api.cluster.state == "NORMAL"

            # full replication restored: every shard lives on BOTH
            # survivors with the right bits
            for shard in range(8):
                for s in servers:
                    frag = (s.holder.index("i").field("f")
                            .view("standard").fragment(shard))
                    assert frag is not None, (shard, s.config.name)
                    assert frag.count_row(1) == 1, (shard, s.config.name)

            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query", b"Count(Row(f=1))")
                assert out["results"] == [8]
        finally:
            for s in servers:
                s.close()

    def test_coordinator_resize_instructions(self, tmp_path):
        """coordinate_resize computes per-node fetch instructions for
        owners missing fragments (reference ResizeInstruction)."""
        import numpy as np

        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            # node1 holds a fragment node0 (also an owner) lacks
            f1 = servers[1].holder.index("i").field("f")
            frag1 = f1.view("standard", create=True).fragment(3, create=True)
            frag1.bulk_import(np.asarray([2, 2], np.uint64),
                              np.asarray([5, 9], np.uint64))

            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            instructions = coord.api.cluster.coordinate_resize()
            assert instructions  # something was computed
            f0 = servers[0].holder.index("i").field("f")
            frag0 = f0.view("standard").fragment(3)
            assert frag0 is not None and frag0.count() == 2
            for s in servers:
                assert s.api.cluster.state == "NORMAL"
        finally:
            for s in servers:
                s.close()

    def test_resize_instruction_uses_fallback_source(self, tmp_path):
        """Coordinator instructions carry extra live holders as
        fallbacks; a receiver whose primary source errors mid-move pulls
        the fragment from a fallback instead of losing it (same contract
        as the self-join inventory)."""
        import numpy as np

        from pilosa_tpu.parallel.client import ClientError, InternalClient

        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            # Drain the join-triggered background resizes (and their
            # synchronous cleanup broadcasts) BEFORE planting: the
            # ~1-in-12 flake was the pending join-resize's cleanup
            # legitimately deleting the planted non-owned copy mid-test,
            # leaving the receiver's fetch with only the broken source.
            # coordinate_resize serializes on the resize lock, so this
            # call returns only after every earlier resize (and its
            # cleanup) finished.
            coord.api.cluster.coordinate_resize()
            peers = [s for s in servers if s is not coord]
            # BOTH peers hold shard 3's fragment; the coordinator (an
            # owner for some shard under replicaN=2) may need to fetch it
            for p in peers:
                fp = p.holder.index("i").field("f")
                fp.view("standard", create=True).fragment(
                    3, create=True
                ).bulk_import(np.asarray([2, 2], np.uint64),
                              np.asarray([5, 9], np.uint64))

            owners = coord.api.cluster.shard_nodes("i", 3)

            def has_frag(s):
                v = s.holder.index("i").field("f").view("standard")
                return v is not None and v.fragment(3) is not None

            receivers = [s for s in servers
                         if any(n.id == s.api.cluster.local.id
                                for n in owners) and not has_frag(s)]
            if not receivers:
                pytest.skip("ring gave shard 3 to its holders only")
            # break the FIRST peer's data endpoint for everyone
            broken_uri = uri(peers[0])
            real_fd = InternalClient.fragment_data

            def flaky(client, node_uri, *a, **k):
                if node_uri == broken_uri:
                    raise ClientError("injected")
                return real_fd(client, node_uri, *a, **k)

            InternalClient.fragment_data = flaky
            try:
                coord.api.cluster.coordinate_resize()
            finally:
                InternalClient.fragment_data = real_fd
            for r in receivers:
                frag = (r.holder.index("i").field("f")
                        .view("standard").fragment(3))
                assert frag is not None and frag.count() == 2, (
                    r.config.name)
        finally:
            for s in servers:
                s.close()

    def test_failed_resize_fetch_leaves_no_empty_placeholder(self, tmp_path):
        """When EVERY source for an instructed move fails, the receiver
        must not keep the eagerly-created empty fragment: an empty
        placeholder serves silently-empty reads for a shard whose data
        exists elsewhere and masks the gap from the self-join
        inventory's already-held check (the resize-source race's second
        half; regression proven to fail pre-fix)."""
        import numpy as np

        from pilosa_tpu.parallel.client import ClientError, InternalClient

        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            coord.api.cluster.coordinate_resize()  # drain join resizes
            peer = next(s for s in servers if s is not coord)
            fp = peer.holder.index("i").field("f")
            fp.view("standard", create=True).fragment(
                3, create=True
            ).bulk_import(np.asarray([2, 2], np.uint64),
                          np.asarray([5, 9], np.uint64))

            def broken(*a, **k):
                raise ClientError("injected: source unreachable")

            real_fd = InternalClient.fragment_data
            real_fb = InternalClient.fragment_blocks
            InternalClient.fragment_data = broken
            InternalClient.fragment_blocks = broken
            try:
                coord.api.cluster.coordinate_resize()
            finally:
                InternalClient.fragment_data = real_fd
                InternalClient.fragment_blocks = real_fb
            v = coord.holder.index("i").field("f").view("standard")
            frag = v.fragment(3) if v is not None else None
            assert frag is None, (
                f"receiver kept an empty placeholder (count="
                f"{frag.count()}) after every source failed"
            )
            # the source's copy is untouched and a later healthy resize
            # still completes the move
            coord.api.cluster.coordinate_resize()
            v = coord.holder.index("i").field("f").view("standard")
            frag = v.fragment(3) if v is not None else None
            assert frag is not None and frag.count() == 2
        finally:
            for s in servers:
                s.close()

    def test_resize_sources_prefer_surviving_owners(self, tmp_path):
        """Instruction sources list holders that REMAIN owners first: a
        non-owner's copy is deleted by this very resize's cleanup, so a
        receiver whose fetch races that cleanup loses a non-owner
        primary source — the root of the ~1-in-12 resize-source flake
        (regression proven to fail pre-fix)."""
        import numpy as np

        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            coord.api.cluster.coordinate_resize()  # drain join resizes
            cluster = coord.api.cluster
            # a shard the COORDINATOR does not own: its two owners are
            # the peers, and the coordinator's planted copy is the
            # non-owner source that must NOT be the primary
            shard = next(
                s for s in range(64)
                if cluster.local.id not in
                {n.id for n in cluster.shard_nodes("i", s)}
            )
            owners = cluster.shard_nodes("i", shard)
            by_id = {s.api.cluster.local.id: s for s in servers}
            src_owner = by_id[owners[0].id]
            receiver = by_id[owners[1].id]
            for holder_server in (coord, src_owner):
                f = holder_server.holder.index("i").field("f")
                f.view("standard", create=True).fragment(
                    shard, create=True
                ).bulk_import(np.asarray([2], np.uint64),
                              np.asarray([5], np.uint64))
            instructions = cluster.coordinate_resize()
            entries = [e for e in instructions.get(
                receiver.api.cluster.local.id, []) if e["shard"] == shard]
            assert entries, instructions
            # pre-fix the holders-walk order made the coordinator (a
            # non-owner, swept by cleanup) the primary source
            assert entries[0]["from"] == src_owner.api.cluster.local.uri, \
                entries
            assert coord.api.cluster.local.uri in entries[0]["fallbacks"]
        finally:
            for s in servers:
                s.close()

    def test_queries_deferred_while_resizing(self, tmp_path):
        import threading
        import time as _time

        servers = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            req("POST", f"{uri(servers[0])}/index/i/query", b"Set(1, f=1)")
            cluster = servers[0].api.cluster
            cluster.state = "RESIZING"
            results = []

            def run():
                out = req("POST", f"{uri(servers[0])}/index/i/query",
                          b"Count(Row(f=1))")
                results.append(out)

            t = threading.Thread(target=run)
            t.start()
            _time.sleep(0.3)
            assert not results  # gated while RESIZING
            cluster.state = "NORMAL"
            t.join(timeout=10)
            assert results and results[0]["results"] == [1]
        finally:
            for s in servers:
                s.close()

    def test_resize_wait_timeout_errors(self, tmp_path, monkeypatch):
        from pilosa_tpu.parallel import cluster_exec

        monkeypatch.setattr(cluster_exec, "_RESIZE_WAIT", 0.2)
        servers = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            servers[0].api.cluster.state = "RESIZING"
            r = urllib.request.Request(
                f"{uri(servers[0])}/index/i/query",
                data=b"Count(Row(f=1))", method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(r, timeout=10)
            assert "resizing" in e.value.read().decode()
        finally:
            for s in servers:
                s.close()


    def test_node_leave_mid_resize_releases_pending(self, tmp_path, monkeypatch):
        """A peer that leaves (or is declared dead) after acking a resize
        instruction is dropped from the pending set immediately — the
        cluster must not stay gated for the full straggler timeout."""
        import threading
        import time as _time

        from pilosa_tpu.parallel.cluster import Cluster

        monkeypatch.setattr(Cluster, "RESIZE_COMPLETE_TIMEOUT", 30.0)
        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            coord, peer = _resize_pair(tmp_path, servers)
            # peer acks the instruction but never fetches nor reports
            peer.api.cluster._run_resize_job = lambda *a, **k: None

            done = threading.Event()
            t = threading.Thread(
                target=lambda: (coord.api.cluster.coordinate_resize(),
                                done.set()),
                daemon=True,
            )
            t.start()
            # wait until the peer is actually pending — a fixed sleep
            # could fire the node-leave before the instruction is sent,
            # passing without exercising the pending-drop path
            deadline = _time.monotonic() + 10
            while not coord.api.cluster._resize_pending:
                assert _time.monotonic() < deadline, "peer never pending"
                _time.sleep(0.01)
            coord.api.cluster.handle_message(
                {"type": "node-leave", "id": peer.api.cluster.local.id}
            )
            assert done.wait(10), "coordinator still gated on departed node"
            assert coord.api.cluster.state == "NORMAL"
        finally:
            for s in servers:
                s.close()


class TestEagerShardVisibility:
    def test_new_remote_shard_visible_without_poll(self, tmp_path):
        """A shard created on one node is broadcast (CreateShardMessage)
        and visible to other nodes' queries immediately — no TTL window
        (VERDICT r1 weak #6)."""
        import time as _time

        servers = make_cluster(tmp_path, 2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            # warm both nodes' shard caches with the empty state
            for s in servers:
                req("POST", f"{uri(s)}/index/i/query", b"Count(Row(f=1))")

            # find a shard owned by node1 alone, import via node1 directly
            c1 = servers[1].api.cluster
            shard = next(s for s in range(64)
                         if c1.shard_nodes("i", s)[0].id == c1.local.id)
            col = shard * SHARD_WIDTH + 3
            req("POST", f"{uri(servers[1])}/index/i/field/f/import",
                {"rows": [1], "columns": [col]})

            # the broadcast is async; wait for receipt (bounded)
            deadline = _time.monotonic() + 5
            while _time.monotonic() < deadline:
                if shard in servers[0].api.cluster.known_shards.get("i", set()):
                    break
                _time.sleep(0.02)
            assert shard in servers[0].api.cluster.known_shards.get("i", set())

            # node0 sees the new shard through its still-warm cache window
            out = req("POST", f"{uri(servers[0])}/index/i/query", b"Row(f=1)")
            assert out["results"][0]["columns"] == [col]
        finally:
            for s in servers:
                s.close()


class TestClusterRaces:
    def test_known_shards_read_during_create_shard_broadcasts(self, tmp_path):
        """ADVICE r2 (medium): _all_shards used to iterate the raw
        known_shards set while handle_message('create-shard') resized it
        from HTTP threads — set.update over a set being resized raises
        RuntimeError mid-query. Hammer both sides concurrently."""
        import threading

        servers = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cluster = servers[0].api.cluster
            execu = servers[0].api.executor
            errors = []
            stop = threading.Event()

            def mutate():
                shard = 0
                while not stop.is_set():
                    shard += 1
                    try:
                        cluster.handle_message({
                            "type": "create-shard", "index": "i",
                            "shards": [shard],
                        })
                    except Exception as e:  # pragma: no cover
                        errors.append(e)

            def read():
                while not stop.is_set():
                    try:
                        execu._all_shards("i")
                    except Exception as e:
                        errors.append(e)

            threads = [threading.Thread(target=mutate) for _ in range(2)]
            threads += [threading.Thread(target=read) for _ in range(2)]
            for t in threads:
                t.start()
            import time as _time
            _time.sleep(1.0)
            stop.set()
            for t in threads:
                t.join(timeout=5)
            assert not errors, errors
        finally:
            for s in servers:
                s.close()

    def test_failover_coordinator_ungates_stuck_resizing(self, tmp_path):
        """ADVICE r2 (medium): coordinator dies between broadcasting
        RESIZING and NORMAL; the failover coordinator finds nothing to
        move (replica_n=1 left no live source) and must STILL broadcast
        NORMAL or peers stay gated forever."""
        servers = make_cluster(tmp_path, 2)
        try:
            # let the join-time background fetch settle first: while a
            # local fetch job is in flight, _command_state correctly
            # DEFERS a NORMAL command (the job's completion restores it),
            # so injecting the scenario early makes the final assert race
            # the join job rather than test the failover path
            for s in servers:
                assert s.api.cluster.wait_until_normal(30)
            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            # simulate the dead coordinator's last act reaching only the
            # peers: the failover coordinator itself stays NORMAL (its
            # RESIZING delivery hit a transient error), peers are gated
            for s in servers:
                if s is not coord:
                    s.api.cluster.state = "RESIZING"
            instructions = coord.api.cluster.coordinate_resize()
            assert instructions == {}  # nothing to move...
            for s in servers:            # ...but everyone un-gated
                assert s.api.cluster.state == "NORMAL", s.config.name
        finally:
            for s in servers:
                s.close()

    def test_async_resize_slow_fetch_gates_queries_no_degrade(self, tmp_path):
        """A fetch slower than instruction delivery must not DEGRADE the
        fetching node or un-gate queries mid-move: peers ack immediately,
        fetch in a worker, and the coordinator holds RESIZING until the
        resize-complete report (reference resize-job pattern)."""
        import threading
        import time as _time

        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            coord = next(s for s in servers
                         if s.api.cluster.is_acting_coordinator)
            peer = next(s for s in servers if s is not coord)
            # the fragment lives on the coordinator; the PEER is the owner
            # that must fetch it, exercising the remote async job path
            fc = coord.holder.index("i").field("f")
            fragc = fc.view("standard", create=True).fragment(3, create=True)
            fragc.bulk_import(np.asarray([2, 2], np.uint64),
                              np.asarray([5, 9], np.uint64))
            peer_cluster = peer.api.cluster

            fetch_started = threading.Event()
            release_fetch = threading.Event()
            real_fetch = type(peer_cluster).fetch_fragments
            states_during_fetch = []

            def slow_fetch(self, sources):
                fetch_started.set()
                assert release_fetch.wait(30)
                return real_fetch(self, sources)

            peer_cluster.fetch_fragments = slow_fetch.__get__(peer_cluster)
            t = threading.Thread(
                target=coord.api.cluster.coordinate_resize, daemon=True
            )
            t.start()
            assert fetch_started.wait(30)
            # mid-move: everyone still gated, nobody DEGRADED
            _time.sleep(0.2)
            states_during_fetch = [
                coord.api.cluster.state,
                next(n.state for n in coord.api.cluster.nodes.values()
                     if n.id == peer_cluster.local.id),
            ]
            release_fetch.set()
            t.join(timeout=30)
            assert not t.is_alive()
            assert states_during_fetch == ["RESIZING", "NORMAL"]
            for s in servers:
                assert s.api.cluster.state == "NORMAL"
            frag0 = (peer.holder.index("i").field("f")
                     .view("standard").fragment(3))
            assert frag0 is not None and frag0.count() == 2
        finally:
            for s in servers:
                s.close()

    def test_async_resize_straggler_timeout_ungates(self, tmp_path, monkeypatch):
        """A peer that never reports completion (died mid-fetch) must not
        gate the cluster forever: the coordinator's straggler timeout
        releases it to anti-entropy repair."""
        from pilosa_tpu.parallel.cluster import Cluster

        monkeypatch.setattr(Cluster, "RESIZE_COMPLETE_TIMEOUT", 0.5)
        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            coord, peer = _resize_pair(tmp_path, servers)
            # peer swallows the instruction: fetch never runs, no report.
            # The message handler gates BEFORE spawning the job and hands
            # the gate to the worker — the swallow must still release it
            # or the peer wedges RESIZING for an unrelated reason.
            pc = peer.api.cluster
            pc.fetch_fragments = lambda sources: 0
            pc._run_resize_job = (
                lambda sources, job, reply_to, pre_gated=False:
                pc._end_local_fetch() if pre_gated else None
            )

            coord.api.cluster.coordinate_resize()
            for s in servers:
                assert s.api.cluster.state == "NORMAL"
        finally:
            for s in servers:
                s.close()

    def test_async_resize_progress_keepalive_outlives_timeout(self, tmp_path, monkeypatch):
        """A move longer than the straggler timeout stays gated to
        completion as long as the peer sends progress keepalives — the
        timeout distinguishes dead from slow, not big from small."""
        import threading
        import time as _time

        from pilosa_tpu.parallel.cluster import Cluster

        monkeypatch.setattr(Cluster, "RESIZE_COMPLETE_TIMEOUT", 0.6)
        monkeypatch.setattr(Cluster, "RESIZE_PROGRESS_INTERVAL", 0.2)
        servers = make_cluster(tmp_path, 2, replica_n=2)
        try:
            coord, peer = _resize_pair(tmp_path, servers)
            peer_cluster = peer.api.cluster
            real_fetch = type(peer_cluster).fetch_fragments
            fetch_done = threading.Event()

            def long_fetch(self, sources):
                # 1.5s of "fetching", far past the 0.6s quiet timeout;
                # the worker's timer thread keeps sending progress
                _time.sleep(1.5)
                out = real_fetch(self, sources)
                fetch_done.set()
                return out

            peer_cluster.fetch_fragments = long_fetch.__get__(peer_cluster)
            coord.api.cluster.coordinate_resize()
            # returned only AFTER the slow move finished (not released by
            # the quiet timeout): the fetch completed and data landed
            assert fetch_done.is_set()
            frag = (peer.holder.index("i").field("f")
                    .view("standard").fragment(3))
            assert frag is not None and frag.count() == 1
            for s in servers:
                assert s.api.cluster.state == "NORMAL"
        finally:
            for s in servers:
                s.close()


class TestBinaryInternalWire:
    def test_routed_bulk_import_transfers_bitmap_bytes(self, tmp_path):
        """A routed set-bit import ships per-shard roaring bodies: the
        bytes on the wire are O(bitmap bytes), not JSON int lists
        (reference: every internal hop is protobuf — SURVEY.md §2 #16-17)."""
        # the edge batch below is deliberately huge (2^18 rows); lift the
        # max-writes-per-request gate that edge imports now enforce
        servers = make_cluster(tmp_path, 2, max_writes_per_request=0)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            sent = []
            for s in servers:
                client = s.api.cluster.client
                real_call = client._call

                def spy(method, url, body=None, _real=real_call, **kw):
                    if body is not None:
                        sent.append((url, len(body)))
                    return _real(method, url, body, **kw)

                client._call = spy
            # 2^17 contiguous bits in each of two shards via ONE node:
            # at least one shard's slice routes to the other node
            n = 1 << 17
            cols = list(range(n)) + [SHARD_WIDTH + c for c in range(n)]
            body = {"rows": [1] * len(cols), "columns": cols}
            req("POST", f"{uri(servers[0])}/index/i/field/f/import", body)
            out = req("POST", f"{uri(servers[0])}/index/i/query",
                      b"Count(Row(f=1))")
            assert out["results"] == [2 * n]
            routed = [(u, sz) for u, sz in sent if "import-roaring" in u]
            assert routed, sent
            total = sum(sz for _, sz in routed)
            # run-encoded roaring: a few hundred bytes for 131k contiguous
            # bits; JSON int lists would be ~1.3 MB. Bound generously.
            assert total < 16 * 1024, (total, routed)
        finally:
            for s in servers:
                s.close()

    def test_remote_row_results_negotiate_protobuf(self, tmp_path):
        """Remote Row() partials come back as protobuf (varint-packed
        columns), decoded to the same shapes the JSON path yields."""
        import pytest as _pytest

        from pilosa_tpu import wire

        if not wire.available():
            _pytest.skip("protoc/protobuf runtime unavailable")
        servers = make_cluster(tmp_path, 2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + c for s in range(4) for c in range(50)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            seen_accept = []
            for s in servers:
                client = s.api.cluster.client
                real_call = client._call

                def spy(method, url, body=None, _real=real_call, **kw):
                    if "/query" in url:
                        seen_accept.append(kw.get("accept"))
                    return _real(method, url, body, **kw)

                client._call = spy
            # query via BOTH nodes: whatever the shard ownership split,
            # at least one of the two must fan out remotely
            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query", b"Row(f=1)")
                assert out["results"][0]["columns"] == sorted(cols)
            assert "application/x-protobuf" in seen_accept
        finally:
            for s in servers:
                s.close()


class TestConcurrentFanout:
    def test_remote_map_cost_is_max_not_sum(self, tmp_path):
        """Cross-node fan-out runs one concurrent sub-query per node
        (reference mapReduce): with two remote nodes each answering in
        ~delay seconds, the query's wall time is ~max(delays), not the
        sum (VERDICT r3 #2)."""
        import time

        servers = make_cluster(tmp_path, 3)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            s0 = servers[0]
            cluster = s0.api.cluster
            shard_for = {}
            for shard in range(64):
                owner = cluster.shard_nodes("i", shard)[0].id
                shard_for.setdefault(owner, shard)
                if len(shard_for) == 3:
                    break
            assert {"n0", "n1", "n2"} <= set(shard_for)
            for node_id, shard in shard_for.items():
                col = shard * SHARD_WIDTH + 1
                req("POST", f"{uri(s0)}/index/i/query",
                    f"Set({col}, f=1)".encode(), content_type="text/plain")
            out = req("POST", f"{uri(s0)}/index/i/query",
                      b"Count(Row(f=1))", content_type="text/plain")
            assert out["results"][0] == 3

            client = s0.api.executor.cluster.client
            orig = client.query_node
            # generous delay: the threshold below leaves ~delay*0.8 of
            # budget for real HTTP/query overhead on a loaded CI machine
            delay = 1.0

            def slow(node_uri, *a, **k):
                time.sleep(delay)
                return orig(node_uri, *a, **k)

            client.query_node = slow
            try:
                t0 = time.monotonic()
                out = req("POST", f"{uri(s0)}/index/i/query",
                          b"Count(Row(f=1))", content_type="text/plain")
                wall = time.monotonic() - t0
            finally:
                client.query_node = orig
            assert out["results"][0] == 3
            # serial fan-out would cost >= 2*delay of pure sleep
            assert wall < 2 * delay * 0.9, f"fan-out not concurrent: {wall:.3f}s"
        finally:
            for s in servers:
                s.close()


class TestAsyncSelfJoin:
    def test_joiner_with_slow_peer_serves_status_and_gates_queries(self, tmp_path):
        """Self-join fetch runs as a background job (VERDICT r3 #8): while
        a slow peer drags the fragment fetch out, Server.open has already
        returned, the joiner answers /status as RESIZING, and queries
        gate on wait_until_normal — then complete correctly once the
        fetch finishes."""
        import threading
        import time

        from pilosa_tpu.parallel.client import InternalClient

        servers = make_cluster(tmp_path, 1)
        late = None
        orig = InternalClient.fragment_data
        started = threading.Event()
        release = threading.Event()

        def slow_fragment_data(self, *a, **k):
            started.set()
            release.wait(30)
            return orig(self, *a, **k)

        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 3 for s in range(16)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})

            InternalClient.fragment_data = slow_fragment_data
            t0 = time.monotonic()
            late = Server(ServerConfig(
                data_dir=str(tmp_path / "late"), port=0, name="n9",
                seeds=[uri(servers[0])], anti_entropy_interval=0,
                heartbeat_interval=0, use_mesh=False,
            )).open()
            open_wall = time.monotonic() - t0
            assert started.wait(10), "join fetch never started"
            # open() returned while the fetch is still blocked
            assert release.is_set() is False
            assert open_wall < 10
            # /status answers mid-fetch and reports the gate
            st = req("GET", f"{uri(late)}/status")
            assert st["state"] == "RESIZING"

            # a query against the joiner gates (does not error, does not
            # return early with partial data)
            result = {}

            def query():
                out = req("POST", f"{uri(late)}/index/i/query",
                          b"Count(Row(f=1))")
                result["count"] = out["results"][0]

            qt = threading.Thread(target=query, daemon=True)
            qt.start()
            qt.join(timeout=0.8)
            assert qt.is_alive(), "query should gate while RESIZING"

            release.set()
            qt.join(timeout=30)
            assert not qt.is_alive()
            assert result["count"] == 16
            assert late.api.cluster.wait_until_normal(10)
            assert req("GET", f"{uri(late)}/status")["state"] == "NORMAL"
        finally:
            InternalClient.fragment_data = orig
            release.set()
            for s in servers + ([late] if late else []):
                s.close()

    def test_normal_command_deferred_while_local_fetch_in_flight(self):
        """A coordinator's NORMAL broadcast arriving while this node is
        still pulling fragments must not un-gate queries mid-fetch; the
        last local fetch job restores the commanded state."""
        from pilosa_tpu.parallel.cluster import Cluster, Node

        c = Cluster(Node("n0", "http://localhost:1"))
        c._begin_local_fetch()
        assert c.state == "RESIZING"
        c.handle_message({"type": "cluster-state", "state": "NORMAL"})
        assert c.state == "RESIZING"  # deferred, not stomped
        c._end_local_fetch()
        assert c.state == "NORMAL"  # restored on last job exit

        # and a RESIZING command outlives the local fetch
        c._begin_local_fetch()
        c.handle_message({"type": "cluster-state", "state": "RESIZING"})
        c._end_local_fetch()
        assert c.state == "RESIZING"
        c.handle_message({"type": "cluster-state", "state": "NORMAL"})
        assert c.state == "NORMAL"


class TestFragmentNodesRoute:
    def test_fragment_nodes_lists_owners(self, tmp_path):
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            out = req("GET",
                      f"{uri(servers[0])}/internal/fragment/nodes"
                      f"?index=i&shard=5")
            ids = {n["id"] for n in out}
            assert len(ids) == 2  # replicaN owners
            want = {n.id for n in
                    servers[0].api.cluster.shard_nodes("i", 5)}
            assert ids == want
        finally:
            for s in servers:
                s.close()


class TestMutexImportRouting:
    def test_clustered_mutex_import_preserves_single_value(self, tmp_path):
        """Routed mutex imports must NOT ride the roaring union route:
        the receiver would keep a column's previous row set while the
        sender's replica cleared it — replica divergence plus a broken
        single-value invariant on the remote owner."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/m",
                {"options": {"type": "mutex"}})
            cols = [s * SHARD_WIDTH + 5 for s in range(6)]
            req("POST", f"{uri(servers[0])}/index/i/field/m/import",
                {"rows": [1] * len(cols), "columns": cols})
            # re-import the same columns under a DIFFERENT row via a
            # different node: every replica must move them, not union
            req("POST", f"{uri(servers[1])}/index/i/field/m/import",
                {"rows": [2] * len(cols), "columns": cols})
            for s in servers:
                url = f"{uri(s)}/index/i/query"
                out = req("POST", url, b"Count(Row(m=1))")
                assert out == {"results": [0]}, s.config.name
                out = req("POST", url, b"Row(m=2)")
                assert out["results"][0]["columns"] == cols, s.config.name
            # and the fragments themselves agree on every replica
            for s in servers:
                f = s.holder.index("i").field("m")
                view = f.view("standard")
                if view is None:
                    continue
                for shard in range(6):
                    frag = view.fragment(shard)
                    if frag is None:
                        continue
                    assert not frag.contains(1, 5), (s.config.name, shard)
        finally:
            for s in servers:
                s.close()
