"""Key translation + attribute storage tests (reference translate.go /
attr.go behavior — SURVEY.md §2 #9–10)."""

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import PQLError
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.attrs import AttrStore
from pilosa_tpu.storage.translate import TranslateStore


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    yield holder, Executor(holder)
    holder.close()


class TestTranslateStore:
    def test_assign_and_lookup(self, tmp_path):
        ts = TranslateStore(str(tmp_path / "t.log")).open()
        assert ts.translate("c/i", ["a", "b", "a"], create=True) == [0, 1, 0]
        assert ts.translate("c/i", ["b", "z"]) == [1, None]
        assert ts.translate("r/i/f", ["a"], create=True) == [0]  # separate ns
        assert ts.keys_of("c/i", [0, 1, 5]) == ["a", "b", None]
        ts.close()

    def test_persistence(self, tmp_path):
        ts = TranslateStore(str(tmp_path / "t.log")).open()
        ts.translate("c/i", ["x", "y"], create=True)
        ts.close()
        ts2 = TranslateStore(str(tmp_path / "t.log")).open()
        assert ts2.translate("c/i", ["y"]) == [1]
        assert ts2.translate("c/i", ["z"], create=True) == [2]
        ts2.close()

    def test_replication_log(self, tmp_path):
        primary = TranslateStore(str(tmp_path / "p.log")).open()
        replica = TranslateStore(str(tmp_path / "r.log")).open()
        primary.translate("c/i", ["a", "b"], create=True)
        replica.apply_log(primary.read_log(0))
        assert replica.translate("c/i", ["a", "b"]) == [0, 1]
        # incremental tail
        offset = primary.log_size()
        primary.translate("c/i", ["c"], create=True)
        replica.apply_log(primary.read_log(offset))
        assert replica.translate("c/i", ["c"]) == [2]
        primary.close(); replica.close()


class TestAttrStore:
    def test_merge_and_null_delete(self, tmp_path):
        s = AttrStore(str(tmp_path / "a.db")).open()
        assert s.set_attrs(5, {"name": "x", "stars": 3}) == {"name": "x", "stars": 3}
        assert s.set_attrs(5, {"stars": 4}) == {"name": "x", "stars": 4}
        assert s.set_attrs(5, {"name": None}) == {"stars": 4}
        assert s.attrs(5) == {"stars": 4}
        assert s.attrs(99) == {}
        s.close()

    def test_blocks_diffing(self, tmp_path):
        a = AttrStore(str(tmp_path / "a.db")).open()
        b = AttrStore(str(tmp_path / "b.db")).open()
        for i in (1, 2, 150):
            a.set_attrs(i, {"v": i})
        b.set_attrs(1, {"v": 1})
        b.set_attrs(2, {"v": 2})
        blocks_a, blocks_b = dict(a.blocks()), dict(b.blocks())
        assert blocks_a[0] == blocks_b[0]  # block 0 identical
        assert 1 in blocks_a and 1 not in blocks_b  # block 1 differs
        b.merge_block(a.block_data(1))
        assert dict(b.blocks()) == blocks_a
        a.close(); b.close()


class TestKeyedQueries:
    def test_column_and_row_keys_end_to_end(self, env):
        holder, ex = env
        holder.create_index("users", keys=True).create_field(
            "likes", FieldOptions(keys=True)
        )
        ex.execute("users", 'Set("alice", likes="pizza")')
        ex.execute("users", 'Set("bob", likes="pizza")')
        ex.execute("users", 'Set("alice", likes="sushi")')
        (res,) = ex.execute("users", 'Row(likes="pizza")')
        assert sorted(res.keys) == ["alice", "bob"]
        assert res.to_json() == {"attrs": {}, "keys": res.keys}
        (n,) = ex.execute(
            "users", 'Count(Intersect(Row(likes="pizza"), Row(likes="sushi")))'
        )
        assert n == 1

    def test_clear_row_and_store_with_row_keys(self, env):
        """ClearRow/Store translate keyed rows like every other write
        (ClearRow of an unknown key is a no-op False; Store creates the
        target row key)."""
        holder, ex = env
        holder.create_index("users", keys=True).create_field(
            "likes", FieldOptions(keys=True)
        )
        ex.execute("users", 'Set("alice", likes="pizza")')
        ex.execute("users", 'Set("bob", likes="pizza")')
        assert ex.execute("users", 'ClearRow(likes="nothing")') == [False]
        # Store the pizza row under a NEW row key
        assert ex.execute(
            "users", 'Store(Row(likes="pizza"), likes="popular")'
        ) == [True]
        (res,) = ex.execute("users", 'Row(likes="popular")')
        assert sorted(res.keys) == ["alice", "bob"]
        assert ex.execute("users", 'ClearRow(likes="pizza")') == [True]
        (res,) = ex.execute("users", 'Row(likes="pizza")')
        assert res.columns().size == 0

    def test_unknown_key_reads_empty(self, env):
        holder, ex = env
        holder.create_index("users", keys=True).create_field(
            "likes", FieldOptions(keys=True)
        )
        ex.execute("users", 'Set("alice", likes="pizza")')
        (res,) = ex.execute("users", 'Row(likes="nothing")')
        assert res.columns().size == 0
        assert ex.execute("users", 'Clear("ghost", likes="pizza")') == [False]

    def test_keys_without_option_rejected(self, env):
        holder, ex = env
        holder.create_index("i").create_field("f")
        with pytest.raises(PQLError):
            ex.execute("i", 'Set("key", f=1)')
        with pytest.raises(PQLError):
            ex.execute("i", 'Set(1, f="key")')

    def test_topn_rows_with_keys(self, env):
        holder, ex = env
        holder.create_index("users", keys=True).create_field(
            "likes", FieldOptions(keys=True)
        )
        for who in ("a", "b", "c"):
            ex.execute("users", f'Set("{who}", likes="pizza")')
        ex.execute("users", 'Set("a", likes="sushi")')
        (pairs,) = ex.execute("users", "TopN(likes, n=2)")
        assert [(p.key, p.count) for p in pairs] == [("pizza", 3), ("sushi", 1)]
        assert pairs[0].to_json()["key"] == "pizza"
        (rows,) = ex.execute("users", "Rows(likes)")
        assert rows == ["pizza", "sushi"]

    def test_keys_persist(self, env, tmp_path):
        holder, ex = env
        holder.create_index("users", keys=True).create_field(
            "likes", FieldOptions(keys=True)
        )
        ex.execute("users", 'Set("alice", likes="pizza")')
        holder.close()
        h2 = Holder(holder.data_dir).open()
        ex2 = Executor(h2)
        (res,) = ex2.execute("users", 'Row(likes="pizza")')
        assert res.keys == ["alice"]
        h2.close()


class TestAttrCalls:
    def test_set_row_attrs_and_result_attachment(self, env):
        holder, ex = env
        holder.create_index("repos").create_field("stargazer")
        ex.execute("repos", "Set(10, stargazer=1)")
        assert ex.execute(
            "repos", 'SetRowAttrs(stargazer, 1, name="alice", active=true)'
        ) == [None]
        (res,) = ex.execute("repos", "Row(stargazer=1)")
        assert res.attrs == {"name": "alice", "active": True}
        assert res.to_json()["attrs"] == {"name": "alice", "active": True}

    def test_set_column_attrs(self, env):
        holder, ex = env
        idx = holder.create_index("repos")
        idx.create_field("f")
        ex.execute("repos", 'SetColumnAttrs(7, owner="bob")')
        assert idx.column_attrs.attrs(7) == {"owner": "bob"}

    def test_row_attrs_with_keyed_field(self, env):
        holder, ex = env
        holder.create_index("users", keys=True).create_field(
            "likes", FieldOptions(keys=True)
        )
        ex.execute("users", 'Set("a", likes="pizza")')
        ex.execute("users", 'SetRowAttrs(likes, "pizza", cuisine="italian")')
        (res,) = ex.execute("users", 'Row(likes="pizza")')
        assert res.attrs == {"cuisine": "italian"}


def test_includes_column_with_keys(env):
    """IncludesColumn(column=) accepts column keys on a keyed index;
    unknown keys resolve to False (not an error)."""
    holder, ex = env
    holder.create_index("users", keys=True).create_field(
        "likes", FieldOptions(keys=True)
    )
    ex.execute("users", 'Set("alice", likes="pizza")')
    assert ex.execute(
        "users", 'IncludesColumn(Row(likes="pizza"), column="alice")'
    ) == [True]
    assert ex.execute(
        "users", 'IncludesColumn(Row(likes="pizza"), column="ghost")'
    ) == [False]
