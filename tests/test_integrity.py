"""Storage integrity plane (ISSUE 10): checksum sidecars + verified
loads, quarantine at open, every-offset corruption fuzz, the background
scrubber (detection, read-repair, self-heal), ENOSPC/EIO degraded mode
with probe auto-recovery, epoch-file hardening, restore read-back
verification, and the CLI check verb."""

from __future__ import annotations

import errno
import glob
import json
import os
import time
import urllib.error

import pytest

from pilosa_tpu.storage import Holder
from pilosa_tpu.storage import integrity
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.integrity import (
    CHECKSUM_SUFFIX,
    CorruptFragmentError,
    StorageHealth,
)
from pilosa_tpu.storage.view import VIEW_STANDARD
from pilosa_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_disk_plane():
    yield
    faults.clear_disk()


def _mk_holder(tmp_path, name="h", **kw):
    return Holder(str(tmp_path / name), **kw).open()


def _frag(holder, index="i", field="f", shard=0):
    idx = holder.index(index) or holder.create_index(index)
    fld = idx.field(field) or idx.create_field(field)
    return fld.view(VIEW_STANDARD, create=True).fragment(shard, create=True)


def _flip(path, offset, mask=0x10):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ mask]))


def _seed_frag(holder, n=60):
    frag = _frag(holder)
    for i in range(n):
        frag.set_bit(1, i * 3)
        frag.set_bit(250, i * 5)
    holder.wal.barrier()
    frag.snapshot()
    return frag


class TestChecksumSidecar:
    def test_snapshot_writes_sidecar_matching_blocks(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        sidecar = integrity.load_checksums(frag.path + CHECKSUM_SUFFIX)
        assert sidecar == list(frag.blocks())
        h.close()

    def test_clean_reopen_verifies(self, tmp_path):
        h = _mk_holder(tmp_path)
        _seed_frag(h)
        h.close()
        before = integrity.global_integrity().metrics()[
            "integrity_verified_loads_total"]
        h2 = _mk_holder(tmp_path)
        assert integrity.global_integrity().metrics()[
            "integrity_verified_loads_total"] > before
        assert _frag(h2).count_row(1) == 60
        h2.close()

    def test_torn_sidecar_reads_as_absent_not_corrupt(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        with open(frag.path + CHECKSUM_SUFFIX, "r+b") as f:
            f.truncate(9)
        h.close()
        h2 = _mk_holder(tmp_path)  # skipped verify, not quarantined
        assert _frag(h2).count_row(1) == 60
        h2.close()

    def test_failed_sidecar_write_cannot_condemn_new_snapshot(
            self, tmp_path):
        """The old sidecar dies BEFORE the new snapshot publishes: a
        crash (or ENOSPC) between the rename and the new sidecar
        landing must leave NO sidecar — the next open downgrades to an
        unverified load instead of quarantining the healthy file
        against stale digests."""
        import pilosa_tpu.storage.fragment as frag_mod

        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)  # snapshot 1: sidecar exists
        frag.set_bit(9, 9)

        def broken(path, blocks):
            raise OSError(28, "No space left on device", path)

        orig = frag_mod.save_checksums
        frag_mod.save_checksums = broken
        try:
            frag.snapshot()  # snapshot 2: sidecar write fails
        finally:
            frag_mod.save_checksums = orig
        assert integrity.load_checksums(
            frag.path + CHECKSUM_SUFFIX) is None  # stale one is GONE
        h.close()
        h2 = _mk_holder(tmp_path)  # unverified load, NOT quarantine
        frag2 = h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        assert frag2 is not None and frag2.contains(9, 9)
        h2.close()

    def test_flipped_payload_byte_quarantines_at_open(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        path = frag.path
        h.close()
        _flip(path, os.path.getsize(path) - 3)
        h2 = _mk_holder(tmp_path)
        view = h2.index("i").field("f").view(VIEW_STANDARD)
        assert view.fragment(0) is None  # never served
        assert not os.path.exists(path)
        assert glob.glob(path + ".quarantine-*")
        assert integrity.list_quarantined(h2.data_dir)
        h2.close()

    def test_verify_off_skips_digest_check(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        path = frag.path
        h.close()
        # flip inside an array payload: structurally valid, wrong bits
        _flip(path, os.path.getsize(path) - 3)
        h2 = Holder(str(tmp_path / "h"), verify_on_load=False).open()
        assert h2.index("i").field("f").view(VIEW_STANDARD).fragment(0) \
            is not None  # the pre-PR behavior, preserved behind the knob
        h2.close()


class TestCorruptionFuzz:
    """The PR-5 torn-tail fuzz, generalized to the whole file: flip or
    truncate at EVERY offset; open must either succeed (the op tail's
    torn-tail crash model) or raise the typed CorruptFragmentError with
    the path in the message — never a raw struct/zlib/index error."""

    def _fragment_file(self, tmp_path):
        frag = Fragment(str(tmp_path / "frag"), "i", "f",
                        VIEW_STANDARD, 0).open()
        for i in range(40):
            frag.set_bit(1, i * 7)
        frag.snapshot()
        for i in range(6):  # op-log tail past the snapshot
            frag.set_bit(2, i)
        frag.close()
        with open(frag.path, "rb") as f:
            return frag.path, f.read(), list(frag.blocks())

    def _reopen(self, path, verify):
        return Fragment(path, "i", "f", VIEW_STANDARD, 0,
                        verify_on_load=verify).open()

    def test_flip_every_offset(self, tmp_path):
        path, data, blocks = self._fragment_file(tmp_path)
        integrity.save_checksums(path + CHECKSUM_SUFFIX, blocks)
        baseline_ops = 6
        for offset in range(len(data)):
            buf = bytearray(data)
            buf[offset] ^= 0x04
            with open(path, "wb") as f:
                f.write(bytes(buf))
            try:
                frag = self._reopen(path, verify=True)
            except CorruptFragmentError as e:
                assert path in str(e)
            except Exception as e:  # noqa: BLE001
                pytest.fail(f"offset {offset}: raw {type(e).__name__}: {e}")
            else:
                # survived: only the (self-CRC'd) op tail may tolerate
                # a flip, by dropping records — never by inventing ops
                assert frag.op_n <= baseline_ops

    def test_truncate_every_offset(self, tmp_path):
        path, data, blocks = self._fragment_file(tmp_path)
        integrity.save_checksums(path + CHECKSUM_SUFFIX, blocks)
        for end in range(len(data)):
            with open(path, "wb") as f:
                f.write(data[:end])
            try:
                self._reopen(path, verify=True)
            except CorruptFragmentError as e:
                assert path in str(e)
            except Exception as e:  # noqa: BLE001
                pytest.fail(f"truncate {end}: raw {type(e).__name__}: {e}")

    def test_import_roaring_garbage_is_typed(self, tmp_path):
        frag = Fragment(str(tmp_path / "frag"), "i", "f",
                        VIEW_STANDARD, 0).open()
        with pytest.raises(CorruptFragmentError):
            frag.import_roaring(b"\x75\xb1\xc4\x50garbage-after-magic")
        # still a ValueError for existing handlers
        with pytest.raises(ValueError):
            frag.import_roaring(b"\x75\xb1\xc4\x50garbage-after-magic")
        frag.close()


class TestScrubber:
    def test_detects_and_self_heals_without_replicas(self, tmp_path):
        from pilosa_tpu.parallel.scrub import Scrubber

        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        live = frag.count_row(1)
        _flip(frag.path, 60)
        s = Scrubber(h)
        rec = s.scrub_pass()
        assert rec["corrupt"] == 1 and rec["self_healed"] == 1, rec
        assert glob.glob(frag.path + ".quarantine-*")
        # disk verifies clean now, live bits preserved
        assert s.scrub_pass()["corrupt"] == 0
        assert _frag(h).count_row(1) == live
        h.close()
        h2 = _mk_holder(tmp_path)
        assert _frag(h2).count_row(1) == live
        h2.close()

    def test_clean_pass_touches_nothing(self, tmp_path):
        from pilosa_tpu.parallel.scrub import Scrubber

        h = _mk_holder(tmp_path)
        _seed_frag(h)
        rec = Scrubber(h).scrub_pass()
        assert rec["corrupt"] == 0 and rec["scanned"] == 1
        assert rec["bytes"] > 0
        assert not integrity.list_quarantined(h.data_dir)
        h.close()

    def test_racing_snapshot_is_not_condemned(self, tmp_path):
        """A mismatch observed unlocked must be re-derived under the
        fragment lock before quarantine acts (a snapshot swapping
        file+sidecar mid-read is a race, not rot)."""
        from pilosa_tpu.parallel.scrub import Scrubber

        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        s = Scrubber(h)
        real = integrity.read_file
        calls = {"n": 0}

        def racy_read(path):
            calls["n"] += 1
            if calls["n"] == 1:
                # first (unlocked) read sees a flipped byte...
                data = bytearray(real(path))
                data[-3] ^= 0x40
                return bytes(data)
            return real(path)  # ...the locked re-read sees the truth

        import pilosa_tpu.storage.integrity as integrity_mod

        orig = integrity_mod.read_file
        integrity_mod.read_file = racy_read
        try:
            rec = s.scrub_pass()
        finally:
            integrity_mod.read_file = orig
        assert rec["corrupt"] == 0 and rec["scanned"] == 1, rec
        assert not glob.glob(frag.path + ".quarantine-*")
        h.close()

    def test_read_repair_via_disk_fault_plane(self, tmp_path):
        """bit-flip-on-read injection (no real file mutation) drives
        the same detect → quarantine → heal path the media-rot case
        takes, proving detection needs no lucky write pattern."""
        from pilosa_tpu.parallel.scrub import Scrubber

        h = _mk_holder(tmp_path)
        frag = _seed_frag(h)
        plane = faults.install_disk()
        plane.add("read", path=frag.path, flip_offset=70, flip_mask=0x02)
        s = Scrubber(h)
        rec = s.scrub_pass()
        # rule is unlimited: both the unlocked read and the locked
        # confirm see the flip — detection + self-heal fire
        assert rec["corrupt"] == 1 and rec["self_healed"] == 1, rec
        faults.clear_disk()
        assert s.scrub_pass()["corrupt"] == 0
        h.close()


class TestStorageDegraded:
    @pytest.fixture()
    def server(self, tmp_path):
        from tests.cluster_helpers import make_cluster

        StorageHealth.PROBE_INTERVAL_S = 0.1
        (s,) = make_cluster(tmp_path, 1)
        try:
            yield s
        finally:
            StorageHealth.PROBE_INTERVAL_S = 1.0
            faults.clear_disk()
            s.close()

    def _req(self, s, method, path, body=None):
        from tests.cluster_helpers import req, uri

        return req(method, f"{uri(s)}{path}", body)

    def test_enospc_on_wal_flips_degraded_and_recovers(self, server):
        s = server
        self._req(s, "POST", "/index/i", {})
        self._req(s, "POST", "/index/i/field/f", {})
        self._req(s, "POST", "/index/i/query", b"Set(1, f=1)")
        plane = faults.install_disk()
        rule = plane.add("fsync", path=s.holder.data_dir,
                         errno_=errno.ENOSPC)
        with pytest.raises(urllib.error.HTTPError):
            self._req(s, "POST", "/index/i/query", b"Set(2, f=1)")
        st = self._req(s, "GET", "/status")
        assert st["storageDegraded"] is True
        assert "No space left" in st["storageDegradedReason"]
        # subsequent writes shed 503 + Retry-After on the QoS path
        with pytest.raises(urllib.error.HTTPError) as err:
            self._req(s, "POST", "/index/i/query", b"Set(3, f=1)")
        assert err.value.code == 503
        assert err.value.headers.get("Retry-After")
        # schema writes shed too
        with pytest.raises(urllib.error.HTTPError) as err:
            self._req(s, "POST", "/index/j", {})
        assert err.value.code == 503
        # reads still serve
        out = self._req(s, "POST", "/index/i/query", b"Count(Row(f=1))")
        assert isinstance(out["results"][0], int)
        # gauge exported
        from tests.cluster_helpers import req, uri

        text = req("GET", f"{uri(s)}/metrics", raw=True).decode()
        assert "storage_degraded 1" in text
        # heal: drop the rule -> the probe clears the latch
        plane.remove(rule.id)
        deadline = time.time() + 10
        while (time.time() < deadline
               and self._req(s, "GET", "/status")["storageDegraded"]):
            time.sleep(0.1)
        assert self._req(s, "GET", "/status")["storageDegraded"] is False
        out = self._req(s, "POST", "/index/i/query", b"Set(4, f=1)")
        assert out["results"] == [True]
        text = req("GET", f"{uri(s)}/metrics", raw=True).decode()
        assert "storage_degraded 0" in text
        assert "storage_recoveries_total 1" in text

    def test_failed_group_never_acks_after_recovery(self, tmp_path):
        """The lost group's barrier must raise FOREVER — clearing the
        fault and committing newer groups past it must not convert the
        lost writes into late ACKs."""
        StorageHealth.PROBE_INTERVAL_S = 0.05
        h = _mk_holder(tmp_path)
        try:
            frag = _frag(h)
            frag.set_bit(1, 1)
            h.wal.barrier()
            plane = faults.install_disk()
            rule = plane.add("fsync", path=h.data_dir,
                             errno_=errno.ENOSPC)
            frag.set_bit(1, 2)
            seq_lost = h.wal.current_seq()
            with pytest.raises(OSError, match="wal commit failed"):
                h.wal.barrier(seq_lost)
            plane.remove(rule.id)
            deadline = time.time() + 5
            while h.health.degraded and time.time() < deadline:
                time.sleep(0.05)
            assert not h.health.degraded
            frag.set_bit(1, 3)  # new group commits fine
            h.wal.barrier()
            with pytest.raises(OSError, match="wal commit failed"):
                h.wal.barrier(seq_lost)  # the lost group stays lost
        finally:
            faults.clear_disk()
            StorageHealth.PROBE_INTERVAL_S = 1.0
            h.close()

    def test_snapshot_enospc_trips_health(self, tmp_path):
        StorageHealth.PROBE_INTERVAL_S = 30.0  # no auto-clear mid-test
        h = _mk_holder(tmp_path)
        try:
            frag = _seed_frag(h)
            plane = faults.install_disk()
            plane.add("fsync", path=frag.path, errno_=errno.ENOSPC,
                      count=1)
            with pytest.raises(OSError):
                frag.snapshot()
            assert h.health.degraded
            assert "snapshot" in h.health.reason
        finally:
            faults.clear_disk()
            StorageHealth.PROBE_INTERVAL_S = 1.0
            h.close()


class TestEpochFile:
    def _cluster(self, tmp_path):
        from pilosa_tpu.parallel.cluster import Cluster, Node

        holder = _mk_holder(tmp_path, "epoch-h")
        return holder, Cluster(Node("n0", "http://localhost:1"),
                               holder=holder)

    def test_garbage_epoch_file_recovers(self, tmp_path):
        holder = _mk_holder(tmp_path, "epoch-h")
        epoch_path = os.path.join(holder.data_dir, "cluster.epoch")
        with open(epoch_path, "wb") as f:
            f.write(b"\x00\xffgarbage\x13\x37")
        holder.close()
        from pilosa_tpu.parallel.cluster import Cluster, Node

        holder2 = Holder(str(tmp_path / "epoch-h")).open()
        c = Cluster(Node("n0", "http://localhost:1"), holder=holder2)
        assert c.epoch == 0
        # file re-persisted clean: the next open parses it
        with open(epoch_path) as f:
            assert int(f.read().strip()) == 0
        # gossip re-adoption still works and persists
        c.adopt_epoch(2048)
        with open(epoch_path) as f:
            assert int(f.read().strip()) == 2048
        holder2.close()

    def test_truncated_epoch_file_recovers(self, tmp_path):
        holder, c0 = self._cluster(tmp_path)
        c0.adopt_epoch(4096)
        epoch_path = os.path.join(holder.data_dir, "cluster.epoch")
        with open(epoch_path, "r+b") as f:
            f.truncate(2)  # "40": parses as a WRONG but valid int? no-
            # truncate to 2 bytes of "4096" -> "40", still an int; make
            # it truly torn instead
        with open(epoch_path, "wb") as f:
            f.write(b"40\x00\x01")
        from pilosa_tpu.parallel.cluster import Cluster, Node

        c = Cluster(Node("n0", "http://localhost:1"), holder=holder)
        assert c.epoch == 0  # torn file -> re-adopt from gossip
        holder.close()

    def test_empty_and_missing_epoch_files(self, tmp_path):
        holder, _ = self._cluster(tmp_path)
        epoch_path = os.path.join(holder.data_dir, "cluster.epoch")
        open(epoch_path, "w").close()
        from pilosa_tpu.parallel.cluster import Cluster, Node

        assert Cluster(Node("n0", "http://x:1"), holder=holder).epoch == 0
        os.unlink(epoch_path)
        assert Cluster(Node("n0", "http://x:1"), holder=holder).epoch == 0
        holder.close()


class TestRestoreVerify:
    def _seed(self, tmp_path):
        h = _mk_holder(tmp_path, "src")
        _seed_frag(h)
        from pilosa_tpu.storage.backup import backup_holder

        backup_holder(h, str(tmp_path / "bk"))
        h.close()

    def test_restore_writes_sidecars_and_verifies(self, tmp_path):
        self._seed(tmp_path)
        from pilosa_tpu.storage.backup import restore_holder

        manifest = restore_holder(str(tmp_path / "bk"),
                                  str(tmp_path / "dst"))
        assert manifest["restoredFragments"] >= 1
        frag_path = os.path.join(
            str(tmp_path / "dst"), "i", "f", "views", VIEW_STANDARD,
            "fragments", "0")
        assert integrity.load_checksums(
            frag_path + CHECKSUM_SUFFIX) is not None
        # restored dir passes a verified open
        h = Holder(str(tmp_path / "dst")).open()
        assert _frag(h).count_row(1) == 60
        h.close()

    def test_corrupt_at_rest_target_fails_restore(self, tmp_path):
        """A restore target that flips bits at rest (injected on the
        read-back seam) is caught AT RESTORE TIME by the live checksum
        verification, not at first query weeks later."""
        self._seed(tmp_path)
        from pilosa_tpu.storage.backup import restore_holder

        plane = faults.install_disk()
        plane.add("read", path=f"{tmp_path}/dst", flip_offset=66)
        with pytest.raises(ValueError, match="digest verification"):
            restore_holder(str(tmp_path / "bk"), str(tmp_path / "dst"))


class TestCLICheck:
    def test_offline_check_clean_and_corrupt(self, tmp_path, capsys):
        from pilosa_tpu.cli import main

        h = _mk_holder(tmp_path, "data")
        frag = _seed_frag(h)
        path = frag.path
        h.close()
        assert main(["check", "-d", str(tmp_path / "data")]) == 0
        out = capsys.readouterr()
        assert "ok:" in out.out
        _flip(path, os.path.getsize(path) - 3)
        assert main(["check", "-d", str(tmp_path / "data")]) == 1
        out = capsys.readouterr()
        assert "CORRUPT" in out.err and "digest mismatch" in out.err

    def test_offline_check_reports_quarantine(self, tmp_path, capsys):
        from pilosa_tpu.cli import main

        h = _mk_holder(tmp_path, "data")
        frag = _seed_frag(h)
        path = frag.path
        h.close()
        _flip(path, os.path.getsize(path) - 3)
        Holder(str(tmp_path / "data")).open().close()  # quarantines
        assert main(["check", "-d", str(tmp_path / "data")]) == 1
        assert "QUARANTINED" in capsys.readouterr().err

    def test_check_requires_target(self, capsys):
        from pilosa_tpu.cli import main

        assert main(["check"]) == 1
        assert "data-dir or --host" in capsys.readouterr().err

    def test_live_check_triggers_scrub(self, tmp_path, capsys):
        from tests.cluster_helpers import make_cluster, uri

        from pilosa_tpu.cli import main

        (s,) = make_cluster(tmp_path, 1)
        try:
            from tests.cluster_helpers import req

            req("POST", f"{uri(s)}/index/i", {})
            req("POST", f"{uri(s)}/index/i/field/f", {})
            req("POST", f"{uri(s)}/index/i/query", b"Set(5, f=1)")
            s.holder.index("i").field("f").view(VIEW_STANDARD) \
                .fragment(0).snapshot()
            assert main(["check", "--host", uri(s)]) == 0
            out = capsys.readouterr().out
            assert "live scrub" in out and "scanned=" in out
        finally:
            s.close()


class TestScrubEndpointAndMetrics:
    def test_internal_scrub_and_metrics_series(self, tmp_path):
        from tests.cluster_helpers import make_cluster, req, uri

        (s,) = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(s)}/index/i", {})
            req("POST", f"{uri(s)}/index/i/field/f", {})
            req("POST", f"{uri(s)}/index/i/query", b"Set(5, f=1)")
            frag = (s.holder.index("i").field("f").view(VIEW_STANDARD)
                    .fragment(0))
            frag.snapshot()
            _flip(frag.path, os.path.getsize(frag.path) - 2)
            rec = req("POST", f"{uri(s)}/internal/scrub", b"")
            assert rec["corrupt"] == 1 and rec["self_healed"] == 1
            text = req("GET", f"{uri(s)}/metrics", raw=True).decode()
            for series in ("integrity_quarantined_total",
                           "integrity_self_heals_total",
                           "scrub_passes_total", "storage_degraded"):
                assert series in text, series
            dv = req("GET", f"{uri(s)}/debug/vars")
            assert "integrity" in dv
            st = req("GET", f"{uri(s)}/status")
            assert st["storageDegraded"] is False
        finally:
            s.close()

    def test_config_knobs_roundtrip(self):
        from pilosa_tpu.server import ServerConfig

        cfg = ServerConfig.from_dict({
            "verify-on-load": "false",
            "scrub-interval": "90s",
            "scrub-max-bytes-per-sec": "1048576",
        })
        assert cfg.verify_on_load is False
        assert cfg.scrub_interval == 90.0
        assert cfg.scrub_max_bytes_per_sec == 1 << 20
        d = cfg.to_dict()
        assert d["verify-on-load"] is False
        assert d["scrub-interval"] == 90.0
        assert d["scrub-max-bytes-per-sec"] == 1 << 20
        with pytest.raises(ValueError, match="scrub-interval"):
            ServerConfig(scrub_interval=-1)


class TestReadRepair:
    def test_two_node_byte_identical_heal(self, tmp_path):
        from tests.cluster_helpers import make_cluster, req, uri

        from pilosa_tpu.parallel.scrub import Scrubber

        a, b = make_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(a)}/index/i", {})
            req("POST", f"{uri(a)}/index/i/field/f", {})
            acked = []
            for col in range(0, 420, 7):
                out = req("POST", f"{uri(a)}/index/i/query",
                          f"Set({col}, f=3)".encode())
                if out["results"] == [True]:
                    acked.append(col)
            for s in (a, b):
                s.holder.index("i").field("f").view(VIEW_STANDARD) \
                    .fragment(0).snapshot()
            frag_b = (b.holder.index("i").field("f").view(VIEW_STANDARD)
                      .fragment(0))
            want = frag_b.serialize_snapshot()
            _flip(frag_b.path, 50, 0x08)
            rec = Scrubber(b.holder, cluster=b.api.cluster).scrub_pass()
            assert rec["corrupt"] == 1 and rec["repaired"] == 1, rec
            healed = (b.holder.index("i").field("f").view(VIEW_STANDARD)
                      .fragment(0))
            assert healed is not None
            assert healed.serialize_snapshot() == want  # byte-identical
            with open(healed.path, "rb") as f:
                assert f.read() == want  # on disk too
            # zero lost acked writes
            got = set(req("POST", f"{uri(b)}/index/i/query",
                          b"Row(f=3)")["results"][0]["columns"])
            assert got == set(acked)
            assert glob.glob(healed.path + ".quarantine-*")
        finally:
            a.close()
            b.close()
