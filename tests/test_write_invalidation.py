"""Write-path invalidation: one Set() patches exactly the affected shard
slot of resident stacked leaves on device instead of purging every leaf
(SURVEY.md §7.3 hard part #3; replaces the round-1 global generation
purge, which made any mixed workload re-upload its working set)."""

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage import residency


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    yield holder, Executor(holder)
    holder.close()


def fill(field, rows, per_row=50, shards=4, stride=17):
    for r in rows:
        for s in range(shards):
            positions = [(i * stride) % SHARD_WIDTH for i in range(per_row)]
            frag = field.view("standard", create=True).fragment(s, create=True)
            frag.bulk_import([r] * len(positions), positions)


def cache():
    return residency.global_row_cache()


class TestSetDoesNotEvictUnrelatedLeaves:
    def test_single_set_patches_in_place(self, env):
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        g = idx.create_field("g")
        fill(f, rows=[1, 2])
        fill(g, rows=[1])

        q = "Count(Intersect(Row(f=1), Row(f=2))) Count(Row(g=1))"
        base = ex.execute("i", q)
        resident_before = len(cache())
        misses_before = cache().misses

        # one Set into f row 1 shard 2
        pos = 3  # not in the stride pattern
        (changed,) = ex.execute("i", f"Set({2 * SHARD_WIDTH + pos}, f=1)")
        assert changed is True

        out = ex.execute("i", q)
        assert out[0] == base[0] + 0  # intersect unchanged (row 2 lacks pos)
        assert out[1] == base[1]
        # leaves were patched, not purged: same residency, zero new decodes
        assert cache().misses == misses_before
        assert len(cache()) == resident_before
        assert cache().updates >= 1

        # and the patched leaf is CORRECT: row 1 now includes the new bit
        (row1,) = ex.execute("i", "Row(f=1)")
        assert 2 * SHARD_WIDTH + pos in set(row1.columns().tolist())
        assert cache().misses == misses_before  # still no re-decode

    def test_clear_bit_patches_single_view_leaf(self, env):
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        fill(f, rows=[1])
        (base,) = ex.execute("i", "Count(Row(f=1))")
        misses = cache().misses
        ex.execute("i", "Clear(0, f=1)")  # position 0 is in the pattern
        (after,) = ex.execute("i", "Count(Row(f=1))")
        assert after == base - 1
        assert cache().misses == misses  # delta-patched, not re-decoded

    def test_bulk_import_patches(self, env):
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        fill(f, rows=[1], shards=2)
        (base,) = ex.execute("i", "Count(Row(f=1))")
        misses = cache().misses
        frag = f.view("standard").fragment(0)
        new_positions = [5, 7, 11]  # stride pattern avoids small odd primes
        before = {int(c) for c in frag.row_columns(1).tolist()}
        frag.bulk_import([1] * 3, new_positions)
        added = len(set(new_positions) - before)
        (after,) = ex.execute("i", "Count(Row(f=1))")
        assert after == base + added
        assert cache().misses == misses

    def test_bsi_write_patches_plane_leaf(self, env):
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("amount", FieldOptions(type="int", min=0, max=1000))
        for col, val in ((0, 10), (1, 20), (SHARD_WIDTH + 2, 30)):
            f.set_value(col, val)
        (s,) = ex.execute("i", "Sum(field=amount)")
        assert s.value == 60
        misses = cache().misses
        f.set_value(2, 40)
        (s2,) = ex.execute("i", "Sum(field=amount)")
        assert s2.value == 100
        assert cache().misses == misses  # plane leaf patched in place

    def test_write_only_invalidates_affected_compressed_leaf(self, env):
        """Presence check at the storage level: a write to field f never
        touches resident leaves of field g (different tag)."""
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        g = idx.create_field("g")
        fill(f, rows=[1], shards=1)
        fill(g, rows=[1], shards=1)
        ex.execute("i", "Count(Row(f=1)) Count(Row(g=1))")
        g_keys = [k for k in cache()._rows if len(k) > 3 and k[3] == "g"]
        assert g_keys
        g_arrs = [cache()._rows[k].arr for k in g_keys]
        ex.execute("i", "Set(9, f=1)")
        for k, arr in zip(g_keys, g_arrs):
            assert cache()._rows[k].arr is arr  # same device buffer


class TestDeleteRecreateSafety:
    def test_field_recreate_does_not_serve_stale_leaves(self, env):
        """Generation-free keys must not leak data across a field
        delete+recreate under the same name."""
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        f.set_bit(1, 10)
        (c1,) = ex.execute("i", "Count(Row(f=1))")
        assert c1 == 1
        idx.delete_field("f")
        f2 = idx.create_field("f")
        f2.set_bit(1, 20)
        (c2,) = ex.execute("i", "Count(Row(f=1))")
        assert c2 == 1
        (row,) = ex.execute("i", "Row(f=1)")
        assert row.columns().tolist() == [20]


class TestConcurrentWritePatching:
    def test_parallel_writers_do_not_lose_patches(self, env):
        """Two writers on different fragments of one field hold different
        fragment locks; the residency lock must serialize their
        read-modify-write of the shared stacked leaf (a lost patch here
        serves a missing bit forever)."""
        import threading

        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        for s in range(2):
            f.view("standard", create=True).fragment(s, create=True)
        f.set_bit(1, 0)
        ex.execute("i", "Count(Row(f=1))")  # leaf resident

        N = 200
        barrier = threading.Barrier(2)

        def writer(shard):
            barrier.wait()
            frag = f.view("standard").fragment(shard)
            for i in range(1, N + 1):
                frag.set_bit(1, i)

        threads = [threading.Thread(target=writer, args=(s,)) for s in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        (count,) = ex.execute("i", "Count(Row(f=1))")
        assert count == 2 * N + 1
        (row,) = ex.execute("i", "Row(f=1)")
        want = {0} | set(range(1, N + 1)) | {SHARD_WIDTH + i for i in range(1, N + 1)}
        assert set(row.columns().tolist()) == want


class TestBufferedBuild:
    def test_write_landing_mid_decode_is_replayed(self, env):
        """A write that lands while a stacked leaf is being decoded (after
        the builder claimed the key, before the upload) must appear in the
        resulting leaf: get_or_build buffers the event and replays it as a
        patch after the upload."""
        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        fill(f, rows=[1])

        from pilosa_tpu.executor import batch

        new_col = 2 * SHARD_WIDTH + 3  # not in the stride pattern
        fired = {"done": False}
        real_host_row = batch.host_row

        def host_row_with_midwrite(idx_, spec, shard):
            out = real_host_row(idx_, spec, shard)
            if not fired["done"] and spec.field == "f":
                fired["done"] = True
                # the builder has already claimed the key and registered
                # the probe; this write must be buffered and replayed
                f.set_bit(1, new_col)
            return out

        batch.host_row = host_row_with_midwrite
        try:
            (row1,) = ex.execute("i", "Row(f=1)")
        finally:
            batch.host_row = real_host_row
        assert fired["done"]
        assert new_col in set(row1.columns().tolist())
        # the resident leaf (not just this query's result) has the bit
        (n,) = ex.execute("i", f"Count(Intersect(Row(f=1), Row(f=1)))")
        (row1b,) = ex.execute("i", "Row(f=1)")
        assert new_col in set(row1b.columns().tolist())

    def test_concurrent_builders_of_one_key_decode_once(self, env):
        """Two threads missing on the same key: the second waits for the
        first build instead of decoding the leaf twice."""
        import threading

        holder, ex = env
        idx = holder.create_index("i", track_existence=False)
        f = idx.create_field("f")
        fill(f, rows=[1])

        from pilosa_tpu.executor import batch

        decodes = []
        entered = threading.Event()
        release = threading.Event()
        real_host_row = batch.host_row

        def slow_host_row(idx_, spec, shard):
            if spec.field == "f" and not decodes:
                decodes.append(1)
                entered.set()
                assert release.wait(20)
            elif spec.field == "f" and shard == 0:
                decodes.append(1)
            return real_host_row(idx_, spec, shard)

        batch.host_row = slow_host_row
        results = []
        try:
            t1 = threading.Thread(
                target=lambda: results.append(ex.execute("i", "Row(f=1)"))
            )
            t1.start()
            assert entered.wait(20)
            t2 = threading.Thread(
                target=lambda: results.append(ex.execute("i", "Row(f=1)"))
            )
            t2.start()
            import time
            time.sleep(0.2)  # t2 reaches the wait on the pending build
            release.set()
            t1.join(20)
            t2.join(20)
        finally:
            batch.host_row = real_host_row
        assert len(results) == 2
        a, b = (set(r[0].columns().tolist()) for r in results)
        assert a == b
        # one build: slow path entered once, per-shard decode not repeated
        # by the second thread (it waited and reused the entry)
        assert sum(decodes) <= 5  # 4 shards + the gate, single build
