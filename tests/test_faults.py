"""Fault-injection plane semantics (pilosa_tpu/testing/faults.py).

The rule engine is the machinery every partition/chaos scenario stands
on, so its own semantics get first-class coverage: matching (src/dst/
route, names vs endpoints, match budgets), the four actions through a
REAL pooled HTTP exchange, partition/heal helpers, the /debug/faults
endpoint, crash-point plumbing, and the zero-overhead-when-off oracle.
"""

import json
import time
import urllib.request

import pytest

from cluster_helpers import make_cluster, req, uri
from pilosa_tpu.parallel.client import ClientError, InternalClient
from pilosa_tpu.testing import faults
from pilosa_tpu.testing.faults import FaultPlane, FaultRule


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.clear()
    faults.disarm_crash_points()
    yield
    faults.clear()
    faults.disarm_crash_points()


class TestRuleMatching:
    def test_wildcards_and_exact(self):
        plane = FaultPlane()
        plane.name_endpoint("n1", "localhost:1111")
        rule = plane.add("drop", src="n0", dst="n1", route="/internal/")
        d = plane.intercept("n0", "localhost:1111", "/internal/schema")
        assert d is not None and d.drop
        # wrong source
        assert plane.intercept("nX", "localhost:1111",
                               "/internal/schema") is None
        # wrong route
        assert plane.intercept("n0", "localhost:1111", "/status") is None
        # endpoint form matches the same rule as the name form
        rule2 = plane.add("drop", dst="localhost:2222")
        assert plane.intercept("anyone", "localhost:2222", "/x") is not None
        assert rule.matched == 1 and rule2.matched == 1

    def test_match_budget_exhausts(self):
        plane = FaultPlane()
        plane.add("drop", count=2)
        assert plane.intercept("a", "h:1", "/").drop
        assert plane.intercept("a", "h:1", "/").drop
        assert plane.intercept("a", "h:1", "/") is None  # budget spent
        assert plane.dropped == 2

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            FaultRule("explode")

    def test_partition_helpers(self):
        plane = FaultPlane()
        plane.partition("a", "b")
        assert plane.intercept("a", "b", "/").drop
        assert plane.intercept("b", "a", "/").drop
        assert plane.heal() == 2
        assert plane.intercept("a", "b", "/") is None
        # asymmetric: only a→b is cut
        plane.partition("a", "b", bidirectional=False)
        assert plane.intercept("a", "b", "/").drop
        assert plane.intercept("b", "a", "/") is None
        plane.heal()
        # isolate cuts both directions for every peer
        plane.isolate("c")
        assert plane.intercept("c", "anything:1", "/").drop
        assert plane.intercept("x", "c", "/").drop

    def test_heal_keeps_non_drop_rules(self):
        plane = FaultPlane()
        plane.partition("a", "b")
        delay = plane.add("delay", delay_ms=1.0)
        plane.heal()
        assert [r.id for r in plane.rules] == [delay.id]


class TestWireActions:
    """Actions applied to REAL pooled exchanges against a live node."""

    @pytest.fixture
    def node(self, tmp_path):
        servers = make_cluster(tmp_path, 1)
        yield servers[0]
        for s in servers:
            s.close()

    def test_drop_surfaces_as_client_error(self, node):
        client = InternalClient()
        assert client.status(uri(node))["state"] == "NORMAL"
        plane = faults.install()
        plane.add("drop", route="/status")
        with pytest.raises(ClientError) as e:
            client.status(uri(node))
        assert e.value.is_node_fault  # transport-shaped, like a partition
        # other routes unaffected
        client._call("GET", f"{uri(node)}/version")

    def test_error_action_synthesizes_status(self, node):
        client = InternalClient()
        plane = faults.install()
        plane.add("error", route="/status", status=503)
        with pytest.raises(ClientError) as e:
            client.status(uri(node))
        assert e.value.status == 503 and e.value.is_node_fault

    def test_delay_action_delays(self, node):
        client = InternalClient()
        plane = faults.install()
        plane.add("delay", route="/status", delay_ms=150)
        t0 = time.monotonic()
        client.status(uri(node))
        assert time.monotonic() - t0 >= 0.14
        assert plane.delayed == 1

    def test_duplicate_action_delivers_twice(self, node):
        client = InternalClient()
        before = node._http.requests_served
        plane = faults.install()
        plane.add("duplicate", route="/status", count=1)
        out = client.status(uri(node))
        assert out["state"] == "NORMAL"
        # the node served the probe twice for one caller-visible request
        assert node._http.requests_served - before == 2

    def test_source_labels_scope_rules(self, node):
        a, b = InternalClient(), InternalClient()
        a.pool.fault_source = "a"
        b.pool.fault_source = "b"
        plane = faults.install()
        plane.add("drop", src="a")
        with pytest.raises(ClientError):
            a.status(uri(node))
        assert b.status(uri(node))["state"] == "NORMAL"


class TestZeroOverheadOff:
    def test_plane_never_consulted_when_uninstalled(self, tmp_path,
                                                    monkeypatch):
        """The off path is one global load + None test: requests must
        succeed even if every plane method is booby-trapped, proving
        nothing touches the plane when none is installed."""
        servers = make_cluster(tmp_path, 1)
        try:
            def boom(*a, **k):  # pragma: no cover - must never run
                raise AssertionError("fault plane consulted while off")

            monkeypatch.setattr(FaultPlane, "intercept", boom)
            client = InternalClient()
            assert client.status(uri(servers[0]))["state"] == "NORMAL"
        finally:
            for s in servers:
                s.close()

    def test_clear_restores_clean_wire(self, tmp_path):
        servers = make_cluster(tmp_path, 1)
        try:
            client = InternalClient()
            plane = faults.install()
            plane.add("drop")
            with pytest.raises(ClientError):
                client.status(uri(servers[0]))
            faults.clear()
            assert client.status(uri(servers[0]))["state"] == "NORMAL"
        finally:
            for s in servers:
                s.close()


class TestCrashPoints:
    def test_armed_point_kills(self, monkeypatch):
        import os
        import signal

        kills = []
        monkeypatch.setattr(os, "kill",
                            lambda pid, sig: kills.append((pid, sig)))
        faults.crash_point("cluster.pre-cleanup")  # unarmed: no-op
        assert kills == []
        faults.arm_crash_point("cluster.pre-cleanup")
        faults.crash_point("cluster.other")  # different point: no-op
        assert kills == []
        faults.crash_point("cluster.pre-cleanup")
        assert kills == [(os.getpid(), signal.SIGKILL)]

    def test_env_armed_point(self, monkeypatch):
        import os
        import signal

        kills = []
        monkeypatch.setattr(os, "kill",
                            lambda pid, sig: kills.append((pid, sig)))
        monkeypatch.setattr(faults, "_ENV_CRASH", "cluster.pre-declare-dead")
        faults.crash_point("cluster.pre-declare-dead")
        assert kills == [(os.getpid(), signal.SIGKILL)]


class TestDebugFaultsEndpoint:
    def test_programmable_over_http(self, tmp_path):
        servers = make_cluster(tmp_path, 1)
        try:
            base = uri(servers[0])
            out = req("GET", f"{base}/debug/faults")
            assert out == {"enabled": False, "rules": []}
            out = req("POST", f"{base}/debug/faults", {
                "rules": [{"action": "error", "route": "/internal/schema",
                           "status": 598}],
            })
            assert out["installed"] and out["rules"]
            # the node's own name→endpoint mapping self-registered
            assert servers[0].api.cluster.local.id in out["names"].values()
            # the rule bites internal clients
            client = InternalClient()
            with pytest.raises(ClientError) as e:
                client.schema(base)
            assert e.value.status == 598
            out = req("GET", f"{base}/debug/faults")
            assert out["enabled"] and out["rules"][0]["matched"] == 1
            # DELETE clears and uninstalls
            r = urllib.request.Request(f"{base}/debug/faults",
                                       method="DELETE")
            with urllib.request.urlopen(r) as resp:
                assert json.loads(resp.read()) == {"enabled": False}
            assert faults.active() is None
            assert client.schema(base) is not None
        finally:
            for s in servers:
                s.close()

    def test_name_addressed_rules_match_remote_nodes(self, tmp_path):
        """post_faults registers EVERY member's name→endpoint, so a
        dst=<peer name> rule posted to one node actually bites traffic
        toward the peer (regression: only the serving node used to
        self-register, making the documented curl example a no-op)."""
        servers = make_cluster(tmp_path, 2)
        try:
            req("POST", f"{uri(servers[0])}/debug/faults", {
                "rules": [{"action": "drop", "src": "n0", "dst": "n1"}],
            })
            with pytest.raises(ClientError):
                servers[0].api.cluster.client.status(uri(servers[1]))
            # reverse direction untouched
            out = servers[1].api.cluster.client.status(uri(servers[0]))
            assert out["state"] == "NORMAL"
        finally:
            for s in servers:
                s.close()

    def test_bad_rule_rejected(self, tmp_path):
        servers = make_cluster(tmp_path, 1)
        try:
            r = urllib.request.Request(
                f"{uri(servers[0])}/debug/faults",
                data=json.dumps({"rules": [{"action": "nope"}]}).encode(),
                method="POST", headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(r)
            assert e.value.code == 400
        finally:
            for s in servers:
                s.close()

    def test_heal_via_http(self, tmp_path):
        servers = make_cluster(tmp_path, 1)
        try:
            base = uri(servers[0])
            req("POST", f"{base}/debug/faults",
                {"rules": [{"action": "drop", "route": "/status"}]})
            client = InternalClient()
            with pytest.raises(ClientError):
                client.status(base)
            out = req("POST", f"{base}/debug/faults", {"heal": True})
            assert out["rules"] == []
            assert client.status(base)["state"] == "NORMAL"
        finally:
            for s in servers:
                s.close()
