"""Pallas kernel tests (interpret mode on CPU; the same code path compiles
via Mosaic on TPU)."""

import numpy as np

from pilosa_tpu.ops.packing import pack_bits
from pilosa_tpu.ops.pallas_kernels import intersect_count_pallas


def test_intersect_count_matches_oracle():
    rng = np.random.default_rng(0)
    n_bits = 1 << 17  # 4096 words per row
    rows = 8
    a_sets = [set(rng.choice(n_bits, 5000, replace=False).tolist()) for _ in range(rows)]
    b_sets = [set(rng.choice(n_bits, 9000, replace=False).tolist()) for _ in range(rows)]
    a = np.stack([pack_bits(sorted(s), n_bits) for s in a_sets])
    b = np.stack([pack_bits(sorted(s), n_bits) for s in b_sets])
    got = int(intersect_count_pallas(a, b, interpret=True))
    want = sum(len(x & y) for x, y in zip(a_sets, b_sets))
    assert got == want


def test_non_divisible_shapes():
    rng = np.random.default_rng(1)
    # rows not a multiple of BLOCK_ROWS, words not of BLOCK_WORDS
    a = rng.integers(0, 1 << 32, (5, 512 * 13), dtype=np.uint64).astype(np.uint32)
    b = rng.integers(0, 1 << 32, (5, 512 * 13), dtype=np.uint64).astype(np.uint32)
    got = int(intersect_count_pallas(a, b, interpret=True))
    want = int(np.bitwise_count(a & b).sum())
    assert got == want
