"""Envelope-drift contract: query_raw vs _serve_result_cache_hit.

server/api.py deliberately maintains the request envelope TWICE: the
miss half (query_raw: admission → execute → ledger/SLO/profile/tracker)
and the hit half (_serve_result_cache_hit: admission → cached bytes →
the same billing). PR 12 shipped the duplication with a comment asking
future editors to keep them in lockstep; this test makes the ask
executable. It reads both function sources and fails when an
envelope-plane call appears in one half but not the other — so adding,
say, a quota debit to query_raw without mirroring it (or explicitly
classifying it execution-only below) breaks CI instead of silently
unbilling every cache hit.
"""

import inspect
import re

from pilosa_tpu.server.api import API


def _src(name: str) -> str:
    return inspect.getsource(getattr(API, name))


# Envelope-plane call sites: anything the request envelope does to the
# QoS/billing/observability planes. The regex is deliberately broad —
# new verbs on these planes are caught without editing the test.
_PLANE_CALL = re.compile(
    r"(?:"
    r"tracker\.\w+"                 # inflight tracking
    r"|inflight\.stage"             # stage labels
    r"|self\.qos\.admission\.\w+"   # admission gate
    r"|self\.cost\.\w+"             # tenant ledger
    r"|self\.slo\.\w+"              # SLO engine
    r"|new_cost_context"            # cost context lifecycle
    r"|activate_cost|deactivate_cost"
    r"|profile_out\.append"         # PROFILE delivery
    r"|on_submitted\(\)"            # dedupe-join cutoff
    r")"
)

# Miss-half calls that legitimately have no mirror in the hit half:
# they only exist because the miss half EXECUTES the query. Everything
# else must appear in both halves.
EXECUTION_ONLY = {
    # the hit half never runs device work, so nothing to attribute —
    # its CostContext is created (for billing) but never activated
    "activate_cost",
    "deactivate_cost",
}

# Hit-half calls whose miss-half equivalents live inside
# _query_raw_admitted / the rescache store path rather than in
# query_raw's own body.
HIT_ONLY = {
    "on_submitted()",
}


def _plane_calls(src: str) -> set:
    return set(_PLANE_CALL.findall(src))


class TestEnvelopeMirror:
    def test_every_miss_plane_call_is_mirrored(self):
        miss = _plane_calls(_src("query_raw"))
        hit = _plane_calls(_src("_serve_result_cache_hit"))
        unmirrored = miss - hit - EXECUTION_ONLY
        assert not unmirrored, (
            f"query_raw's envelope gained plane calls the cache-hit "
            f"mirror lacks: {sorted(unmirrored)} — update "
            f"_serve_result_cache_hit (server/api.py) or classify them "
            f"in EXECUTION_ONLY here"
        )

    def test_hit_half_invents_no_planes(self):
        miss = _plane_calls(_src("query_raw"))
        hit = _plane_calls(_src("_serve_result_cache_hit"))
        # verbs only the hit half performs must be explicitly listed —
        # an unexplained extra usually means the mirror drifted the
        # other way
        extras = hit - miss - HIT_ONLY
        assert not extras, (
            f"_serve_result_cache_hit performs plane calls query_raw "
            f"never does: {sorted(extras)}"
        )

    def test_error_envelope_shape(self):
        """Both halves classify outcomes identically: ApiError keeps its
        status, anything else is a 500, sheds (429) bill the ledger but
        not the SLO."""
        for name in ("query_raw", "_serve_result_cache_hit"):
            src = _src(name)
            assert "except ApiError as e:" in src, name
            assert "err_status = e.status" in src, name
            assert re.search(r"except Exception:\s*\n\s*err_status = 500",
                             src), name
            assert "finally:" in src, name
            assert "err_status != 429" in src, (
                f"{name}: SLO must skip shed (429) outcomes"
            )
            assert "err_status is not None and err_status >= 500" in src, (
                f"{name}: ledger error flag must mean 5xx only"
            )

    def test_admission_shed_contract(self):
        """Both halves surface admission sheds as ApiError 429 with the
        Retry-After hint, gated on pre_admitted."""
        for name in ("query_raw", "_serve_result_cache_hit"):
            src = _src(name)
            assert "self.qos.admission.admit(tenant)" in src, name
            assert "ApiError(str(e), 429)" in src, name
            assert "err.retry_after = e.retry_after" in src, name
            assert "pre_admitted" in src, name

    def test_billing_mirror_flags(self):
        """The hit half bills record_query with result_cache_hit=True
        only when the cached bytes were actually served (not on a shed);
        the miss half never sets the flag."""
        hit = _src("_serve_result_cache_hit")
        assert "result_cache_hit=err_status is None" in hit
        miss = _src("query_raw")
        assert "result_cache_hit" not in miss
