"""End-to-end tests for the multi-process serving tier (ISSUE 11):
SO_REUSEPORT worker subprocesses + one device-owner over pickle-free
shared-memory rings (pilosa_tpu/serving/).

Covers the contracts the subsystem must carry across the IPC boundary:
byte-identical responses vs the owner's own handler, WAL-barrier ACK
semantics (a 200 through a worker still means fsynced — proven by
SIGKILLing the owner mid-burst), tenant/cost and trace attribution
surviving the hop, degraded-mode shedding answered worker-side, ring
backpressure as 429, dead-worker respawn, owner-restart re-handshake,
and the single-process fallback on platforms without SO_REUSEPORT."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.server import Server, ServerConfig

pytestmark = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"),
    reason="multi-process serving needs SO_REUSEPORT",
)


def _req(port, method, path, body=None, headers=None, timeout=30):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body, method=method, headers=headers or {},
    )
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return resp.status, resp.read()


def _query(port, pql, headers=None, timeout=30):
    return _req(port, "POST", "/index/i/query", pql.encode(),
                headers=headers, timeout=timeout)


@pytest.fixture(scope="module")
def mp_server(tmp_path_factory):
    """One 2-worker server shared by the read-path tests; every request
    is sampled so trace attribution is assertable."""
    server = Server(ServerConfig(
        data_dir=str(tmp_path_factory.mktemp("mp")), port=0,
        serving_workers=2, ring_slots=128, ring_slot_bytes=8192,
        trace_sample_rate=1.0,
        anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
    )).open()
    try:
        assert server._mpserve is not None, "mp serving did not start"
        port = server.port
        _req(port, "POST", "/index/i", b"{}")
        _req(port, "POST", "/index/i/field/f", b"{}")
        for col, row in ((1, 1), (2, 1), (70, 2)):
            st, _ = _query(port, f"Set({col}, f={row})")
            assert st == 200
        yield server
    finally:
        server.close()


class TestEndToEnd:
    def test_ring_and_proxy_routes_serve(self, mp_server):
        port = mp_server.port
        st, body = _query(port, "Count(Row(f=1))")
        assert (st, json.loads(body)) == (200, {"results": [2]})
        # schema (proxied) and the worker-local debug route
        st, body = _req(port, "GET", "/schema")
        assert st == 200 and json.loads(body)["indexes"][0]["name"] == "i"
        st, body = _req(port, "GET", "/debug/worker")
        stats = json.loads(body)
        assert st == 200 and stats["requests"] >= 1
        assert stats["worker"] in (0, 1)

    def test_responses_byte_identical_to_owner_handler(self, mp_server):
        """The deployment shape must be invisible to clients: the same
        queries through a worker's ring and through the owner's own
        loopback listener produce identical bytes."""
        owner_port = mp_server._mpserve.owner_port
        queries = ["Count(Row(f=1))", "Row(f=2)", "TopN(f)",
                   "Count(Intersect(Row(f=1), Row(f=2)))"]
        for pql in queries:
            _, via_worker = _query(mp_server.port, pql)
            _, via_owner = _query(owner_port, pql)
            assert via_worker == via_owner, pql

    def test_errors_cross_the_ring_with_status(self, mp_server):
        # unknown index: ApiError from the owner, same text either way
        st_w = body_w = None
        try:
            _req(mp_server.port, "POST", "/index/nope/query",
                 b"Count(Row(f=1))")
        except urllib.error.HTTPError as e:
            st_w, body_w = e.code, e.read()
        try:
            _req(mp_server._mpserve.owner_port, "POST",
                 "/index/nope/query", b"Count(Row(f=1))")
        except urllib.error.HTTPError as e:
            assert (st_w, body_w) == (e.code, e.read())
        assert st_w is not None
        # parse garbage: rejected worker-side before crossing the ring
        before = mp_server._mpserve.metrics()["serving_ring_queries_total"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _query(mp_server.port, "NotAQuery(((")
        assert ei.value.code == 400
        after = mp_server._mpserve.metrics()["serving_ring_queries_total"]
        assert after == before

    def test_observability_surfaces(self, mp_server):
        port = mp_server.port
        st, body = _req(port, "GET", "/debug/workers")
        table = json.loads(body)
        assert table["enabled"] and len(table["workers"]) == 2
        assert all(w["alive"] for w in table["workers"])
        st, body = _req(port, "GET", "/status")
        assert len(json.loads(body)["servingWorkers"]) == 2
        st, body = _req(port, "GET", "/metrics")
        text = body.decode()
        assert "serving_workers 2" in text
        assert "serving_ring_queries_total" in text
        assert "serving_ring_full_total" in text
        assert "serving_owner_batch_size" in text
        st, body = _req(port, "GET", "/debug/vars")
        assert json.loads(body)["serving_mp"]["serving_workers"] == 2

    def test_tenant_and_trace_attribution_survive_the_hop(self, mp_server):
        """The cost plane bills the worker-submitted request to its
        tenant (including response egress), and the owner's
        /debug/traces shows ONE stitched tree: the worker-side edge
        root with the owner-side rpc.query subtree grafted under it."""
        port = mp_server.port
        st, body = _query(port, "Count(Row(f=1))",
                          headers={"X-Pilosa-Tenant": "acct-7"})
        assert st == 200
        deadline = time.monotonic() + 10
        row = None
        while time.monotonic() < deadline and row is None:
            _, tbody = _req(port, "GET", "/debug/tenants")
            for r in json.loads(tbody)["tenants"]:
                if r["tenant"] == "acct-7":
                    row = r
            if row is None:
                time.sleep(0.1)
        assert row is not None, "tenant acct-7 never reached the ledger"
        assert row["queries"] >= 1
        assert row["egress_bytes"] > 0
        # the finished tree arrives over the handshake channel slightly
        # after the response — poll the owner's trace ring
        deadline = time.monotonic() + 10
        tree = None
        while time.monotonic() < deadline and tree is None:
            _, tr = _req(port, "GET", "/debug/traces")
            for t in json.loads(tr)["traces"]:
                blob = json.dumps(t)
                if (t.get("name") == "http.query"
                        and t.get("tags", {}).get("worker")
                        and "rpc.query" in blob):
                    tree = t
            if tree is None:
                time.sleep(0.1)
        assert tree is not None, \
            "no stitched worker-edge tree reached the owner tracer"

    def test_ring_backpressure_sheds_429(self, mp_server):
        """A full submit ring is the backpressure signal: the worker
        answers 429 + Retry-After without queueing anything."""
        mp = mp_server._mpserve
        # saturate the owner pool so drains block and the ring fills
        permits = 0
        while mp._capacity.acquire(blocking=False):
            permits += 1
        assert permits > 0
        # burst more requests than the ring holds; with the owner
        # draining nothing, the overflow must shed 429
        codes = []
        lock = threading.Lock()

        def probe():
            try:
                st, _ = _query(mp_server.port, "Row(f=1)", timeout=30)
            except urllib.error.HTTPError as e:
                st = e.code
                if st == 429:
                    assert e.headers.get("Retry-After")
                e.read()
            with lock:
                codes.append(st)

        threads = [threading.Thread(target=probe) for _ in range(300)]
        try:
            for t in threads:
                t.start()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                with lock:
                    if 429 in codes:
                        break
                time.sleep(0.05)
        finally:
            for _ in range(permits):
                mp._capacity.release()
            for t in threads:
                t.join(60)
        assert 429 in codes, f"no shed in {sorted(set(codes))}"
        # everything that wasn't shed completed once capacity returned
        assert set(codes) <= {200, 429}

    # --- lifecycle drills LAST: they bump worker generations/pids ---

    def test_sigkill_worker_respawns_and_owner_never_wedges(self, mp_server):
        port = mp_server.port
        mp = mp_server._mpserve
        _, body = _req(port, "GET", "/debug/workers")
        victims = {w["pid"] for w in json.loads(body)["workers"]}
        os.kill(sorted(victims)[0], signal.SIGKILL)
        # the owner must keep serving throughout (surviving worker or
        # respawn) — retry over fresh connections, never wedge
        deadline = time.monotonic() + 30
        served = 0
        while time.monotonic() < deadline and served < 5:
            try:
                st, _ = _query(port, "Count(Row(f=1))", timeout=5)
                served += 1 if st == 200 else 0
            except (urllib.error.URLError, OSError):
                time.sleep(0.1)
        assert served >= 5, "owner wedged after a worker SIGKILL"
        assert mp.wait_workers(2, timeout=30), "dead worker not respawned"
        m = mp.metrics()
        assert m["serving_worker_respawns_total"] >= 1
        assert m["serving_workers"] == 2

    def test_owner_restart_workers_rehandshake(self, mp_server):
        mp = mp_server._mpserve
        gens_before = [w["gen"] for w in mp.workers_json()]
        mp.simulate_restart()
        assert mp.wait_workers(2, timeout=30), \
            "workers did not re-handshake after owner restart"
        gens_after = [w["gen"] for w in mp.workers_json() if w["alive"]]
        assert min(gens_after) > min(gens_before)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            try:
                st, body = _query(mp_server.port, "Count(Row(f=1))",
                                  timeout=5)
                if st == 200 and json.loads(body) == {"results": [2]}:
                    return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.1)
        raise AssertionError("serving did not recover after owner restart")


def test_dedupe_followers_share_one_execution(tmp_path):
    """Identical untraced reads that land while a leader's wave has
    not yet submitted join it owner-side: one execution, N byte-equal
    responses, follower-grade accounting. Needs an UNSAMPLED server —
    a traced request carries its own span context and is never
    dedupe-eligible."""
    server = Server(ServerConfig(
        data_dir=str(tmp_path), port=0, serving_workers=2,
        anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
    )).open()
    try:
        port = server.port
        _req(port, "POST", "/index/i", b"{}")
        _req(port, "POST", "/index/i/field/f", b"{}")
        assert _query(port, "Set(70, f=2)")[0] == 200
        mp = server._mpserve
        real = server.api.query_json_bytes

        def slow(*a, **kw):
            time.sleep(0.25)  # hold the leader open past the burst
            return real(*a, **kw)

        server.api.query_json_bytes = slow
        try:
            results = []
            lock = threading.Lock()

            def one():
                r = _query(port, "Count(Row(f=2))", timeout=30)
                with lock:
                    results.append(r)

            threads = [threading.Thread(target=one) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
        finally:
            server.api.query_json_bytes = real
        assert len(results) == 6
        assert {st for st, _ in results} == {200}
        assert {body for _, body in results} == {b'{"results":[1]}'}
        assert mp.deduped > 0
        # follower-grade accounting: the ledger saw all 6 queries
        snap = {r["tenant"]: r for r in server.api.cost.snapshot()}
        assert snap["default"]["queries"] >= 6
        assert snap["default"]["egress_bytes"] > 0
    finally:
        server.close()


class TestDegradedShedding:
    def test_storage_degraded_sheds_worker_side(self, tmp_path):
        """Writes shed 503 AT THE WORKER from the shared control block
        — no ring round-trip — while reads keep serving; recovery
        un-sheds within a flags tick."""
        from pilosa_tpu.serving import mpserve as mpsrv
        from pilosa_tpu.testing import faults

        server = Server(ServerConfig(
            data_dir=str(tmp_path), port=0, serving_workers=1,
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
        )).open()
        plane = faults.install_disk()
        try:
            port = server.port
            _req(port, "POST", "/index/i", b"{}")
            _req(port, "POST", "/index/i/field/f", b"{}")
            assert _query(port, "Set(1, f=1)")[0] == 200
            health = server.holder.health
            health.PROBE_INTERVAL_S = 0.2
            rule = plane.add("fsync", path=str(tmp_path),
                             errno_=28)  # ENOSPC
            health.trip("test: disk full")
            # wait for the degraded flag to reach the control block:
            # until it does, writes still cross the ring and the OWNER
            # sheds them authoritatively; once it lands, the worker
            # sheds WITHOUT a ring round-trip — observable as a 503
            # whose request never moved the ring counter
            def ring_total():
                return server._mpserve.metrics()[
                    "serving_ring_queries_total"]

            deadline = time.monotonic() + 10
            shed = None
            while time.monotonic() < deadline and shed is None:
                before = ring_total()
                try:
                    _query(port, "Set(2, f=1)", timeout=5)
                except urllib.error.HTTPError as e:
                    body = e.read()
                    if e.code == 503 and ring_total() == before:
                        shed = body  # worker-side: no ring crossing
                time.sleep(0.1)
            assert shed is not None, \
                "write never shed worker-side while degraded"
            assert b"storage degraded" in shed
            # reads still serve while writes shed
            assert _query(port, "Count(Row(f=1))")[0] == 200
            # heal: probe clears the latch, flags tick, writes resume
            plane.remove(rule.id)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                try:
                    if _query(port, "Set(3, f=1)", timeout=5)[0] == 200:
                        break
                except urllib.error.HTTPError:
                    time.sleep(0.2)
            else:
                raise AssertionError("writes never resumed after heal")
        finally:
            faults.clear_disk()
            server.close()


class TestFallbackAndConfig:
    def test_no_reuseport_falls_back_to_single_process(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.delattr(socket, "SO_REUSEPORT")
        server = Server(ServerConfig(
            data_dir=str(tmp_path), port=0, serving_workers=2,
            anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
        )).open()
        try:
            assert server._mpserve is None
            st, body = _req(server.port, "GET", "/debug/workers")
            assert json.loads(body) == {"enabled": False, "workers": []}
            # the metrics block still exists, zeroed
            _, body = _req(server.port, "GET", "/metrics")
            assert "serving_workers 0" in body.decode()
        finally:
            server.close()

    def test_tls_is_single_process_only(self, tmp_path):
        from pilosa_tpu.serving.mpserve import mp_unsupported_reason

        cfg = ServerConfig(data_dir=str(tmp_path), serving_workers=2,
                           tls_certificate="/c", tls_key="/k")
        assert "TLS" in mp_unsupported_reason(cfg)

    @pytest.mark.parametrize("kw", [
        {"serving_workers": -1}, {"serving_workers": 1000},
        {"ring_slots": 1}, {"ring_slot_bytes": 16},
    ])
    def test_config_validation(self, tmp_path, kw):
        with pytest.raises(ValueError):
            ServerConfig(data_dir=str(tmp_path), **kw)


def test_kill_a_worker_chaos_schedule(tmp_path):
    """One seeded kill-a-worker schedule through the chaos harness
    (testing/chaos.py MpServingChaos — the shape the default chaos
    config runs): zero lost acked writes, owner never wedges."""
    from pilosa_tpu.testing.chaos import MpServingChaos

    harness = MpServingChaos(str(tmp_path), n_workers=2, seed=7,
                             n_kills=2, kill_gap_s=0.5)
    try:
        harness.boot()
        record = harness.run_schedule()
    finally:
        harness.close()
    assert record["acked_writes"] > 0
    assert record["lost_acked_writes"] == 0, record["lost_sample"]
    assert record["owner_wedges"] == []
    assert record["ok"]


# ------------------------------------------------- subprocess WAL oracle


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_mp(tmp_path, port, workers=2):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "0",
        "PILOSA_TPU_HEARTBEAT_INTERVAL": "0",
        "PILOSA_TPU_USE_MESH": "false",
        "PILOSA_TPU_DURABILITY_MODE": "group",
        "PILOSA_TPU_SERVING_WORKERS": str(workers),
        # orphaned workers give up fast so the restarted owner's fresh
        # workers own the reuseport group without a long steal window
        "PILOSA_TPU_MP_REHANDSHAKE_S": "2",
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu", "server",
         "--data-dir", str(tmp_path / "owner"), "--bind", "127.0.0.1",
         "--port", str(port)],
        env=env, cwd=repo_root,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )
    for _ in range(240):
        if proc.poll() is not None:
            raise AssertionError(f"server exited rc={proc.returncode}")
        try:
            _req(port, "GET", "/status", timeout=5)
            return proc
        except Exception:
            time.sleep(0.25)
    proc.terminate()
    raise AssertionError("mp server never served /status")


def test_wal_ack_barrier_survives_owner_sigkill(tmp_path):
    """The durability contract through a worker: every write a client
    saw 200-acked via the SO_REUSEPORT port is in the fsynced WAL, so
    SIGKILLing the device owner mid-burst (workers die orphaned, no
    clean shutdown anywhere) loses none of them. Attribution rides the
    same hop: the tenant ledger on the owner bills the worker-submitted
    writes before the kill."""
    port = _free_port()
    proc = _spawn_mp(tmp_path, port)
    workers_killed: list[int] = []
    try:
        _req(port, "POST", "/index/i", b"{}")
        _req(port, "POST", "/index/i/field/f", b"{}")
        acked: set[int] = set()
        lock = threading.Lock()
        stop = threading.Event()
        n_writers = 4

        def writer(tid):
            k = 0
            while not stop.is_set():
                col = tid + k * n_writers
                k += 1
                try:
                    st, body = _query(
                        port, f"Set({col}, f=1)",
                        headers={"X-Pilosa-Tenant": "writer-tenant"},
                        timeout=10)
                except Exception:
                    return  # the kill landed mid-request: unacked
                if st == 200 and json.loads(body) == {"results": [True]}:
                    with lock:
                        acked.add(col)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_writers)]
        for t in threads:
            t.start()
        deadline = time.time() + 60
        while True:
            with lock:
                if len(acked) >= 40:
                    break
            assert time.time() < deadline, "burst stalled"
            time.sleep(0.02)
        # attribution check mid-flight, through a worker's proxy route
        _, tbody = _req(port, "GET", "/debug/tenants")
        tenants = {r["tenant"]: r for r in json.loads(tbody)["tenants"]}
        assert tenants["writer-tenant"]["queries"] >= 1
        # find the worker pids (to reap later), then SIGKILL the owner
        _, wbody = _req(port, "GET", "/debug/workers")
        workers_killed = [w["pid"] for w in json.loads(wbody)["workers"]
                          if w["pid"]]
        proc.kill()
        proc.wait(15)
        stop.set()
        for t in threads:
            t.join(15)
        with lock:
            acked_now = set(acked)
        # orphaned workers must give up and exit (owner stays gone
        # beyond their re-handshake window) — the no-zombie half of the
        # dead-peer contract
        deadline = time.time() + 20
        while time.time() < deadline:
            if not any(_pid_alive(p) for p in workers_killed):
                break
            time.sleep(0.25)
        assert not any(_pid_alive(p) for p in workers_killed), \
            "orphaned workers outlived their owner"
        # restart on the same port: every acked write must be there
        proc = _spawn_mp(tmp_path, port)
        got: set[int] = set()
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                st, body = _query(port, "Row(f=1)", timeout=10)
            except Exception:
                time.sleep(0.25)
                continue
            got = set(json.loads(body)["results"][0]["columns"])
            if acked_now <= got:
                break
            time.sleep(0.25)
        missing = acked_now - got
        assert not missing, \
            f"lost {len(missing)} worker-ACKed writes: {sorted(missing)[:5]}"
        # and the restarted shape still serves writes end to end
        assert _query(port, "Set(999999, f=2)")[0] == 200
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(15)
        for p in workers_killed:
            if _pid_alive(p):
                os.kill(p, signal.SIGKILL)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True
