"""Range-keyed placement table (elastic plane): sub-shard column
ranges riding the override table's epoch stamp.

The contract these tests pin is the same mixed-version discipline the
override table itself carries, extended one level down: a table with NO
ranges is byte-identical to plain override/hash placement, a split
ALWAYS travels with a whole-shard override equal to the union of its
range owners (so an override-unaware peer computes identical data
placement from overrides alone), and ranges refine READ preference
only — a reader that ignores them still reads correct bytes from any
union owner."""

import json
import random

from test_autopilot import _bare_cluster, _reference_owners

from pilosa_tpu.parallel.cluster import PlacementTable
from pilosa_tpu.shardwidth import SHARD_WIDTH

HALF = SHARD_WIDTH // 2


class TestByteIdentityFallback:
    def test_no_ranges_byte_identical_across_random_memberships(self):
        """Randomized: a table with overrides but ZERO ranges leaves
        shard_nodes equal to the override/hash walk and range_read_nodes
        always None — the empty-ranges fallback contract."""
        rng = random.Random(2293)
        for _ in range(30):
            n = rng.randint(2, 7)
            ids = rng.sample([f"node-{i}" for i in range(32)], n)
            replica_n = rng.randint(1, 3)
            c = _bare_cluster(ids, replica_n=replica_n)
            assert c.placement.range_count == 0
            for _ in range(20):
                index = rng.choice(["i", "t"])
                shard = rng.randint(0, 500)
                got = [x.id for x in c.shard_nodes(index, shard)]
                assert got == _reference_owners(
                    list(c.nodes.values()), replica_n, index, shard)
                assert c.range_read_nodes(
                    index, shard, rng.randrange(SHARD_WIDTH)) is None

    def test_split_data_placement_is_the_union_override(self):
        """A split's whole-shard ownership comes from its union
        override; range_read_nodes refines per-offset reads to the
        covering span's owner."""
        c = _bare_cluster(["n0", "n1", "n2"], replica_n=1)
        spans = ((0, HALF, ("n0",)), (HALF, SHARD_WIDTH, ("n1",)))
        assert c.placement.replace(
            {("i", 0): ("n0", "n1")}, epoch=1024,
            ranges={("i", 0): spans})
        assert [x.id for x in c.shard_nodes("i", 0)] == ["n0", "n1"]
        assert [x.id for x in c.range_read_nodes("i", 0, 0)] == ["n0"]
        assert [x.id for x in c.range_read_nodes("i", 0, HALF - 1)] \
            == ["n0"]
        assert [x.id for x in c.range_read_nodes("i", 0, HALF)] == ["n1"]
        # other shards are untouched by the split
        assert c.range_read_nodes("i", 1, 0) is None

    def test_departed_range_owner_falls_back_to_union_routing(self):
        """A span whose owner left the membership stops refining —
        range_read_nodes returns None and reads fall back to the
        union/hash owners (who all hold the full fragment)."""
        c = _bare_cluster(["n0", "n1", "n2"], replica_n=1)
        spans = ((0, HALF, ("n0",)), (HALF, SHARD_WIDTH, ("n1",)))
        c.placement.replace({("i", 0): ("n0", "n1")}, epoch=1024,
                            ranges={("i", 0): spans})
        with c._lock:
            c.nodes.pop("n1")
            c._note_membership_changed_locked()
        assert c.range_read_nodes("i", 0, HALF) is None
        # the surviving span still refines
        assert [x.id for x in c.range_read_nodes("i", 0, 0)] == ["n0"]


class TestRangeWriteSpans:
    def test_unsplit_shard_returns_none(self):
        c = _bare_cluster(["n0", "n1"], replica_n=1)
        assert c.range_write_spans("i", 0) is None
        c.placement.replace({("i", 0): ("n0",)}, epoch=8)
        assert c.range_write_spans("i", 0) is None

    def test_split_shard_yields_per_span_owner_slices(self):
        c = _bare_cluster(["n0", "n1", "n2"], replica_n=1)
        spans = ((0, HALF, ("n0",)), (HALF, SHARD_WIDTH, ("n1", "n2")))
        c.placement.replace(
            {("i", 0): ("n0", "n1", "n2")}, epoch=1024,
            ranges={("i", 0): spans})
        got = c.range_write_spans("i", 0)
        assert [(lo, hi, [x.id for x in nodes])
                for lo, hi, nodes in got] \
            == [(0, HALF, ["n0"]),
                (HALF, SHARD_WIDTH, ["n1", "n2"])]

    def test_departed_span_owner_yields_none_owners_for_that_span(self):
        """The half-live-split contract: the caller must union-fan-out
        columns of the departed span (a narrowed send could strand the
        slice), while the surviving span keeps narrowing."""
        c = _bare_cluster(["n0", "n1", "n2"], replica_n=1)
        spans = ((0, HALF, ("n0",)), (HALF, SHARD_WIDTH, ("n1",)))
        c.placement.replace({("i", 0): ("n0", "n1")}, epoch=1024,
                            ranges={("i", 0): spans})
        with c._lock:
            c.nodes.pop("n1")
            c._note_membership_changed_locked()
        got = c.range_write_spans("i", 0)
        assert [x.id for x in got[0][2]] == ["n0"]
        assert got[1][2] is None
        assert (got[0][:2], got[1][:2]) == ((0, HALF),
                                            (HALF, SHARD_WIDTH))


class TestMixedVersionGossip:
    def test_old_peer_adopts_overrides_only_same_data_placement(self):
        """An override-unaware (older) peer parses the gossiped table
        through from_wire, which has no notion of the "ranges" key —
        it must land on the IDENTICAL data placement from the union
        overrides alone."""
        new = _bare_cluster(["n0", "n1", "n2"], replica_n=1)
        spans = ((0, HALF, ("n1",)), (HALF, SHARD_WIDTH, ("n2",)))
        assert new.placement.replace(
            {("i", 0): ("n1", "n2"), ("i", 3): ("n0",)}, epoch=1024,
            ranges={("i", 0): spans})
        wire = new.placement.to_json()
        assert "ranges" in wire  # the new node gossips them

        old = _bare_cluster(["n0", "n1", "n2"], replica_n=1)
        # an older replace() has no ranges parameter to pass: adopt
        # the overrides exactly as its from_wire would produce them
        assert old.placement.replace(
            PlacementTable.from_wire(wire["overrides"]),
            epoch=wire["epoch"])
        assert old.placement.range_count == 0
        for shard in range(8):
            assert ([x.id for x in old.shard_nodes("i", shard)]
                    == [x.id for x in new.shard_nodes("i", shard)])

    def test_ranges_wire_round_trip_skips_malformed(self):
        ranges = {("i", 0): ((0, HALF, ("a",)),
                             (HALF, SHARD_WIDTH, ("b", "c"))),
                  ("j", 7): ((0, SHARD_WIDTH, ("a",)),)}
        entries = PlacementTable.wire_ranges(ranges)
        assert PlacementTable.ranges_from_wire(entries) == ranges
        entries.append({"index": "k"})  # no shard
        entries.append({"index": "k", "shard": 1,
                        "spans": [{"lo": 5, "hi": 5, "nodes": ["a"]}]})
        entries.append({"index": "k", "shard": 2,
                        "spans": [{"lo": 0, "hi": 9, "nodes": []}]})
        entries.append("garbage")
        assert PlacementTable.ranges_from_wire(entries) == ranges

    def test_replace_without_ranges_drops_splits(self):
        """A plain move plan (or an older coordinator) replacing the
        table without ranges drops every split — correct, because the
        matching union overrides are gone too."""
        t = PlacementTable()
        assert t.replace({("i", 0): ("a", "b")}, epoch=5,
                         ranges={("i", 0): ((0, HALF, ("a",)),
                                            (HALF, SHARD_WIDTH, ("b",)))})
        assert t.range_count == 2
        assert t.replace({("i", 1): ("c",)}, epoch=6)
        assert t.range_count == 0
        assert t.get_ranges("i", 0) is None

    def test_clean_ranges_drops_empty_and_inverted_spans(self):
        t = PlacementTable()
        assert t.replace(
            {("i", 0): ("a", "b")}, epoch=5,
            ranges={("i", 0): ((HALF, 0, ("a",)),      # inverted
                               (0, HALF, ()),           # no owner
                               (HALF, SHARD_WIDTH, ("b",)))})
        assert t.get_ranges("i", 0) == ((HALF, SHARD_WIDTH, ("b",)),)


class TestPersistence:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "placement")
        t = PlacementTable(path=path)
        spans = ((0, HALF, ("a",)), (HALF, SHARD_WIDTH, ("b",)))
        assert t.replace({("i", 0): ("a", "b"), ("j", 2): ("c",)},
                         epoch=2048, ranges={("i", 0): spans})
        reloaded = PlacementTable(path=path)
        assert reloaded.epoch == 2048
        assert reloaded.get("i", 0) == ("a", "b")
        assert reloaded.get("j", 2) == ("c",)
        assert reloaded.get_ranges("i", 0) == spans
        assert reloaded.range_count == 2

    def test_persisted_file_is_valid_json_with_ranges_key(self, tmp_path):
        path = str(tmp_path / "placement")
        t = PlacementTable(path=path)
        t.replace({("i", 0): ("a",)}, epoch=7,
                  ranges={("i", 0): ((0, SHARD_WIDTH, ("a",)),)})
        with open(path) as f:
            d = json.load(f)
        assert d["epoch"] == 7
        assert d["ranges"][0]["spans"][0] == {
            "lo": 0, "hi": SHARD_WIDTH, "nodes": ["a"]}

    def test_corrupt_file_starts_empty(self, tmp_path):
        path = str(tmp_path / "placement")
        t = PlacementTable(path=path)
        t.replace({("i", 0): ("a",)}, epoch=7,
                  ranges={("i", 0): ((0, SHARD_WIDTH, ("a",)),)})
        with open(path, "wb") as f:
            f.write(b'{"epoch": 7, "ranges": [tor')
        reloaded = PlacementTable(path=path)
        assert reloaded.epoch == 0
        assert len(reloaded) == 0 and reloaded.range_count == 0

    def test_unsplit_persists(self, tmp_path):
        """A later replace that merges the split back must not leave
        the stale ranges in the persisted file."""
        path = str(tmp_path / "placement")
        t = PlacementTable(path=path)
        t.replace({("i", 0): ("a", "b")}, epoch=5,
                  ranges={("i", 0): ((0, HALF, ("a",)),
                                     (HALF, SHARD_WIDTH, ("b",)))})
        t.replace({("i", 0): ("a", "b")}, epoch=6)
        reloaded = PlacementTable(path=path)
        assert reloaded.epoch == 6
        assert reloaded.range_count == 0
