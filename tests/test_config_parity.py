"""Knob-parity contract: every ServerConfig field must round-trip
through ALL FOUR config surfaces — to_dict/from_dict (kebab), the
snake_case spelling from_dict also accepts, the env-var path (string
values, `PILOSA_TPU_FOO_BAR` → `foo-bar`), and the generated TOML
template (`pilosa-tpu config`). Fields are ENUMERATED from the
constructor signature, so adding a knob without wiring every surface
fails here instead of shipping a knob that silently ignores its env
var (the drift this test was written to stop: several newer knobs
answered only to kebab until the normalization fix in from_dict)."""

import inspect

from pilosa_tpu import cli
from pilosa_tpu.server.server import ServerConfig

# Fields whose "just perturb the default" heuristic would trip
# validation or needs a domain-shaped value.
_NON_DEFAULT = {
    "durability_mode": "per-op",
    "seeds": ["http://seed-a:10101", "http://seed-b:10101"],
    "slo_objectives": ["reads:latency:100ms:0.99", "avail:errors:0.999"],
    "slo_windows": ["60s", "600s"],
    "use_mesh": True,          # default None = auto
    "device_budget_bytes": 123456,  # default None = auto
    "qos_hedge_budget": 0.5,
    "trace_sample_rate": 0.5,
    "autopilot_heat_budget": 2.5,
}

# Knobs that ride the [tls] TOML section in the generated template
# (the flat tls-* spellings are what to_dict emits and from_dict
# prefers; the section is the operator-facing spelling).
_TEMPLATE_SPELLING = {
    "tls_certificate": "certificate",
    "tls_key": "key",
    "tls_skip_verify": "skip-verify",
}


def _fields() -> dict:
    """name → default, from the constructor signature (the single
    source of truth for the knob surface)."""
    sig = inspect.signature(ServerConfig.__init__)
    return {name: p.default for name, p in sig.parameters.items()
            if name != "self"}


def _non_default(name, default):
    if name in _NON_DEFAULT:
        return _NON_DEFAULT[name]
    if isinstance(default, bool):
        return not default
    if isinstance(default, int):
        return default + 3
    if isinstance(default, float):
        return default + 1.5
    if isinstance(default, str):
        return default + "/nondefault" if default else "nondefault"
    if default is None:
        raise AssertionError(
            f"field {name!r} defaults to None: add it to _NON_DEFAULT "
            "so the parity contract covers it"
        )
    raise AssertionError(f"no non-default rule for {name!r} ({default!r})")


def _env_string(value) -> str:
    """How the value looks arriving via PILOSA_TPU_* (cli._load_config
    passes env values through as raw strings)."""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, list):
        return ",".join(str(v) for v in value)
    return str(value)


class TestKnobParity:
    def test_every_field_survives_to_dict_from_dict(self):
        fields = _fields()
        cfg = ServerConfig(**{n: _non_default(n, d)
                              for n, d in fields.items()})
        rebuilt = ServerConfig.from_dict(cfg.to_dict())
        for name in fields:
            assert getattr(rebuilt, name) == getattr(cfg, name), (
                f"{name} lost in to_dict→from_dict round-trip"
            )
        assert rebuilt.to_dict() == cfg.to_dict()

    def test_every_field_accepts_kebab_and_snake(self):
        for name, default in _fields().items():
            value = _non_default(name, default)
            for key in (name.replace("_", "-"), name):
                got = getattr(ServerConfig.from_dict({key: value}), name)
                assert got == getattr(ServerConfig(**{name: value}), name), (
                    f"{name} not settable via from_dict key {key!r}"
                )

    def test_every_field_parses_env_style_strings(self):
        """Env vars deliver strings; every knob must parse its string
        rendering (the exact dict cli._load_config builds)."""
        for name, default in _fields().items():
            value = _non_default(name, default)
            kebab = name.replace("_", "-")
            cfg = ServerConfig.from_dict({kebab: _env_string(value)})
            want = getattr(ServerConfig(**{name: value}), name)
            assert getattr(cfg, name) == want, (
                f"{name} does not parse its env-var string "
                f"{_env_string(value)!r}"
            )

    def test_env_key_mapping_matches_load_config(self, monkeypatch):
        """The documented PILOSA_TPU_FOO_BAR → foo-bar mapping, through
        the real cli._load_config, for a representative of each parse
        family (bool, duration, int, float, str, list)."""
        samples = {
            "PILOSA_TPU_AUTOPILOT_ENABLED": "true",
            "PILOSA_TPU_AUTOPILOT_INTERVAL": "90s",
            "PILOSA_TPU_AUTOPILOT_MAX_MOVES": "7",
            "PILOSA_TPU_AUTOPILOT_HEAT_BUDGET": "2.5",
            "PILOSA_TPU_DURABILITY_MODE": "per-op",
            "PILOSA_TPU_SEEDS": "http://a:1,http://b:2",
        }
        for k, v in samples.items():
            monkeypatch.setenv(k, v)
        cfg = ServerConfig.from_dict(cli._load_config(None))
        assert cfg.autopilot_enabled is True
        assert cfg.autopilot_interval == 90.0
        assert cfg.autopilot_max_moves == 7
        assert cfg.autopilot_heat_budget == 2.5
        assert cfg.durability_mode == "per-op"
        assert cfg.seeds == ["http://a:1", "http://b:2"]

    def test_every_field_appears_in_generated_config(self):
        """`pilosa-tpu config` must mention every knob (commented-out
        entries count — the template is the discovery surface)."""
        template = cli._DEFAULT_TOML
        for name in _fields():
            spelling = _TEMPLATE_SPELLING.get(
                name, name.replace("_", "-"))
            assert spelling in template, (
                f"knob {name} ({spelling!r}) missing from the "
                "generated config template"
            )

    def test_template_round_trips_through_toml(self):
        """The generated template itself must parse as TOML and load
        into a ServerConfig (uncommented defaults only)."""
        try:
            import tomllib
        except ImportError:
            import tomli as tomllib

        parsed = tomllib.loads(cli._DEFAULT_TOML)
        cfg = ServerConfig.from_dict(parsed)
        # template documents the shipped defaults for the autopilot
        assert cfg.autopilot_enabled is False
        assert cfg.autopilot_interval == 30.0
        assert cfg.autopilot_heat_budget == 1.5
