"""Serving-path pipelining: ClusterExecutor.submit + the coalescing
HTTP query pipeline (server/pipeline.py).

The reference serves N concurrent queries with ~linear throughput via
per-request mapReduce goroutines (SURVEY.md §2 #12, §3.2). On a TPU
backend the equivalent property is DISPATCH sharing: concurrent requests
must coalesce into micro-batched device programs instead of each paying
the host→device latency floor. These tests pin (a) result equivalence
between the pipelined and eager paths, over HTTP and in-process, and
(b) the coalescing itself, by counting batched-program builds.
"""

import threading
import urllib.request

import pytest

from cluster_helpers import make_cluster, req, seed, uri
from pilosa_tpu.server.pipeline import QueryPipeline
from pilosa_tpu.shardwidth import SHARD_WIDTH

READ_QUERIES = [
    "Count(Row(f=1))",
    "Row(f=2)",
    "Union(Row(f=1), Row(f=2))",
    "Count(Intersect(Row(f=1), Row(f=2)))",
    'Sum(Row(f=1), field="v")',
    'Min(field="v")',
    'Max(field="v")',
    "TopN(f, n=3)",
    "TopN(f, n=10, threshold=15)",
    "Rows(f)",
    "Rows(f, limit=1)",
    "GroupBy(Rows(f))",
    "GroupBy(Rows(f), having=Condition(count > 8))",
    "Options(Count(Row(f=1)), shards=[0, 2])",
    "Count(Not(Row(f=1)))",
]


class TestClusterSubmit:
    """ClusterExecutor.submit: pipelined results == eager execute, with
    real remote fan-out (3 nodes, shards spread across them)."""

    def test_submit_matches_execute_across_nodes(self, tmp_path):
        servers = make_cluster(tmp_path, 3)
        try:
            seed(servers[0])
            ex = servers[1].api.executor  # a non-coordinator node
            want = [ex.execute("i", q)[0] for q in READ_QUERIES]
            # submit the WHOLE stream first, then resolve — the remote
            # fan-outs and local enqueues of all queries overlap
            defs = [ex.submit("i", q)[0] for q in READ_QUERIES]
            got = [d.result() for d in defs]
            from pilosa_tpu.executor.result import result_to_json

            for q, g, w in zip(READ_QUERIES, got, want):
                assert result_to_json(g) == result_to_json(w), q
        finally:
            for s in servers:
                s.close()

    def test_submit_remote_flag_stays_local(self, tmp_path):
        """remote=True sub-queries must evaluate strictly locally (no
        re-fan-out), same as execute(remote=True)."""
        servers = make_cluster(tmp_path, 2)
        try:
            seed(servers[0])
            for s in servers:
                local_shards = sorted(
                    s.holder.index("i").available_shards()
                )
                want = s.api.executor.execute(
                    "i", "Count(Row(f=1))", shards=local_shards, remote=True
                )
                got = [
                    d.result() for d in s.api.executor.submit(
                        "i", "Count(Row(f=1))", shards=local_shards,
                        remote=True,
                    )
                ]
                assert got == want
        finally:
            for s in servers:
                s.close()


class TestHTTPServing:
    """Concurrent HTTP clients against one server: results must equal
    serial execution and the wave pipeline must coalesce dispatches."""

    N_THREADS = 24

    def _concurrent(self, url, queries):
        results = [None] * len(queries)
        errors = []
        gate = threading.Event()

        def worker(k, q):
            gate.wait(10)
            try:
                results[k] = req("POST", url, q.encode())
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append((q, e))

        threads = [
            threading.Thread(target=worker, args=(k, q))
            for k, q in enumerate(queries)
        ]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(60)
        assert not errors, errors[:3]
        return results

    def test_concurrent_load_matches_serial_mesh_on(self, tmp_path):
        """The VERDICT load test: mesh-backed single-node server, N
        concurrent clients, per-query results identical to serial."""
        servers = make_cluster(tmp_path, 1, use_mesh=True)
        try:
            seed(servers[0])
            url = f"{uri(servers[0])}/index/i/query"
            queries = [
                READ_QUERIES[k % len(READ_QUERIES)]
                for k in range(self.N_THREADS)
            ]
            serial = [req("POST", url, q.encode()) for q in queries]
            concurrent = self._concurrent(url, queries)
            assert concurrent == serial
            pipe = servers[0].api._pipeline
            assert pipe is not None and pipe.waves >= 1
        finally:
            servers[0].close()

    def test_wave_coalesces_same_shape_counts(self, tmp_path):
        """Deterministic dispatch accounting: hold the wave gate until
        every request is queued, then count batched-program builds — 32
        same-shape Counts must share micro-batched dispatches instead of
        paying 32."""
        servers = make_cluster(tmp_path, 1, use_mesh=True)
        try:
            seed(servers[0])
            api = servers[0].api
            n = 32

            class Gated(QueryPipeline):
                def __init__(self, api, expected):
                    super().__init__(api)
                    self.expected = expected
                    self.arrived = 0
                    self.alock = threading.Lock()
                    self.gate = threading.Event()

                def run(self, index, query, kwargs, key=None):
                    with self.alock:
                        self.arrived += 1
                        if self.arrived >= self.expected:
                            self.gate.set()
                    self.gate.wait(30)
                    # key deliberately NOT forwarded: this test counts
                    # device dispatches across DISTINCT submits, so the
                    # identical-query dedupe (covered by its own tests)
                    # must stay out of the way
                    return super().run(index, query, kwargs)

            dist = api.executor.local
            url = f"{uri(servers[0])}/index/i/query"
            queries = [
                f"Count(Intersect(Row(f={1 + (k % 2)}), Row(f=2)))"
                for k in range(n)
            ]
            serial_want = req("POST", url, queries[0].encode())
            api._pipeline = Gated(api, n)

            builds = []
            orig = dist._program_batched

            def counting(structure, rk, lr, ns, nq):
                builds.append(nq)
                return orig(structure, rk, lr, ns, nq)

            dist._program_batched = counting
            out = self._concurrent(url, queries)
            dist._program_batched = orig
            for k, q in enumerate(queries):
                if q == queries[0]:
                    assert out[k] == serial_want
            # all queries went through batched programs, in far fewer
            # dispatches than queries (ideally 1-4 waves); batch sizes
            # pad to powers of two (at most 2x the real rows)
            assert n <= sum(builds) <= 2 * n, builds
            assert len(builds) <= n // 2, builds
            assert all(b & (b - 1) == 0 for b in builds), builds
        finally:
            servers[0].close()

    def test_gather_window_coalesces_under_pressure(self):
        """_gather unit behavior: under pressure (small inter-arrival
        gap) the dispatcher holds the wave open and absorbs stragglers;
        with sparse traffic it returns immediately with no window wait.
        Generous timings so a loaded CI box cannot flake the assertion
        in the strict direction (stretched sleeps only ADD stragglers
        to the window)."""
        import time as _time

        pipe = QueryPipeline(api=None)
        pipe.GATHER_WINDOW_S = 0.25
        pipe._recent_gap = 0.0  # pressure: arrivals back-to-back
        for i in range(3):
            pipe._q.put(i)  # already queued: greedy drain picks up

        def feeder():
            for i in range(5):
                _time.sleep(0.01)
                pipe._q.put(100 + i)

        t = threading.Thread(target=feeder)
        t.start()
        wave = [pipe._q.get()]
        pipe._gather(wave)
        t.join()
        # 1 + 2 drained + stragglers caught inside the 250 ms window;
        # floor not equality: a stretched CI scheduler can push late
        # feeder puts past the deadline, never add extras
        assert 4 <= len(wave) <= 8, len(wave)

        pipe._recent_gap = 1.0  # sparse: no pressure
        pipe._q.put(1)
        wave = [pipe._q.get()]
        t0 = _time.monotonic()
        pipe._gather(wave)
        assert _time.monotonic() - t0 < 0.05  # zero-wait fast path
        assert len(wave) == 1

        # already-queued items are free: the greedy drain is unbounded
        # (a mixed-shape backlog must reach one submit), while the
        # WINDOW phase stops waiting at the cap
        pipe._recent_gap = 0.0
        n = pipe.GATHER_CAP + 5
        for i in range(n):
            pipe._q.put(i)
        wave = [pipe._q.get()]
        t0 = _time.monotonic()
        pipe._gather(wave)
        assert len(wave) == n, len(wave)  # all n drained, none left
        # and the full wave means the window never opened (no 2 ms wait
        # beyond at most one timed get)
        assert _time.monotonic() - t0 < 0.1

    def test_mixed_reads_and_writes_concurrent(self, tmp_path):
        """Writes take the eager routed path, reads the pipeline —
        interleaved concurrent traffic must neither deadlock nor lose
        writes."""
        servers = make_cluster(tmp_path, 1, use_mesh=False)
        try:
            seed(servers[0])
            url = f"{uri(servers[0])}/index/i/query"
            ops = []
            for k in range(16):
                if k % 4 == 0:
                    ops.append(f"Set({7 * SHARD_WIDTH + k}, f=9)")
                else:
                    ops.append("Count(Row(f=1))")
            out = self._concurrent(url, ops)
            for k, op in enumerate(ops):
                if op.startswith("Set"):
                    assert out[k] == {"results": [True]}
            final = req("POST", url, b"Count(Row(f=9))")
            assert final == {"results": [4]}
        finally:
            servers[0].close()

    def test_read_falls_back_to_surviving_replica(self, tmp_path):
        """A replica that fails its sub-query is marked DEGRADED and its
        shards are retried on surviving replicas — a single-replica
        fault must not 500 a read when live replicas hold the data."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            n_shards = 16
            seed(servers[0], n_shards=n_shards)
            url = f"{uri(servers[0])}/index/i/query"
            assert req("POST", url, b"Count(Row(f=1))") == {
                "results": [4 * n_shards]
            }
            # pick the victim DETERMINISTICALLY: a node that node 0's
            # router would actually target first for some shard it does
            # not replicate itself (ring assignment is deterministic)
            cluster0 = servers[0].api.cluster
            routed_first = set()
            for s in range(n_shards):
                ns = cluster0.shard_nodes("i", s)
                if not any(n.id == "n0" for n in ns):
                    routed_first.add(ns[0].id)
            assert routed_first, "every shard is local to n0?"
            victim = next(s for s in servers[1:]
                          if s.api.cluster.local.id in routed_first)
            victim._http.shutdown()
            victim._http.server_close()
            for q, want in [
                (b"Count(Row(f=1))", [4 * n_shards]),
                (b"TopN(f, n=2)",
                 [[{"id": 1, "count": 4 * n_shards},
                   {"id": 2, "count": 2 * n_shards}]]),
            ]:
                assert req("POST", url, q) == {"results": want}, q
            states = {
                n.id: n.state
                for n in servers[0].api.cluster.sorted_nodes()
            }
            assert states[victim.api.cluster.local.id] == "DEGRADED", states
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass

    def test_rowwide_write_tolerates_dead_replica(self, tmp_path):
        """Store/ClearRow skip an unreachable replica (DEGRADED) instead
        of 500ing after the live replicas already applied the write."""
        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            seed(servers[0], n_shards=8)
            victim = servers[2]
            victim._http.shutdown()
            victim._http.server_close()
            url = f"{uri(servers[0])}/index/i/query"
            assert req("POST", url, b"Store(Row(f=1), f=9)") == {
                "results": [True]
            }
            assert req("POST", url, b"ClearRow(f=2)") == {"results": [True]}
            assert req("POST", url, b"Count(Row(f=9))") == {"results": [32]}
            assert req("POST", url, b"Count(Row(f=2))") == {"results": [0]}
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass

    def test_4xx_from_replica_is_not_a_node_fault(self, tmp_path,
                                                  monkeypatch):
        """A deterministic query rejection (HTTP 4xx) from a remote
        replica must propagate to the client — every replica would
        answer identically, so retrying siblings and DEGRADING the
        healthy node would poison routing for one bad query."""
        from pilosa_tpu.parallel.client import ClientError, InternalClient

        servers = make_cluster(tmp_path, 3, replica_n=1)
        try:
            seed(servers[0], n_shards=8)
            real = InternalClient.query_node
            calls = {"n": 0}

            def reject(client, uri, index, pql, shards, remote=True):
                if "Count" in pql:
                    calls["n"] += 1
                    raise ClientError("injected 400", status=400)
                return real(client, uri, index, pql, shards, remote=remote)

            monkeypatch.setattr(InternalClient, "query_node", reject)
            url = f"{uri(servers[0])}/index/i/query"
            with pytest.raises(urllib.error.HTTPError) as ei:
                req("POST", url, b"Count(Row(f=1))")
            # surfaces as a CLIENT error (400), not 'internal' 500
            assert ei.value.code == 400, ei.value.code
            # only first-choice replicas were tried — 2 remote groups
            # from node 0 (nodes n1 and n2), no sibling retries
            assert 1 <= calls["n"] <= 2, calls
            states = {n.id: n.state
                      for n in servers[0].api.cluster.sorted_nodes()}
            assert all(s == "NORMAL" for s in states.values()), states
        finally:
            for s in servers:
                s.close()

    def test_404_schema_lag_retries_sibling_without_degrading(
        self, tmp_path, monkeypatch
    ):
        """A 404 from a replica is ambiguous (could be schema lag, not a
        bad query): the read must retry the shard's sibling replica and
        succeed, and the lagging node must NOT be marked DEGRADED."""
        from pilosa_tpu.parallel.client import ClientError, InternalClient

        servers = make_cluster(tmp_path, 3, replica_n=2)
        try:
            n_shards = 16
            seed(servers[0], n_shards=n_shards)
            cluster0 = servers[0].api.cluster
            routed_first = set()
            for s in range(n_shards):
                ns = cluster0.shard_nodes("i", s)
                if not any(n.id == "n0" for n in ns):
                    routed_first.add(ns[0].id)
            victim = next(s for s in servers[1:]
                          if s.api.cluster.local.id in routed_first)
            victim_port = victim.port
            real = InternalClient.query_node

            def lag(client, node_uri, index, pql, shards, remote=True):
                if str(victim_port) in node_uri and "Count" in pql:
                    raise ClientError("index 'i' not found", status=404)
                return real(client, node_uri, index, pql, shards,
                            remote=remote)

            monkeypatch.setattr(InternalClient, "query_node", lag)
            url = f"{uri(servers[0])}/index/i/query"
            assert req("POST", url, b"Count(Row(f=1))") == {
                "results": [4 * n_shards]
            }
            states = {n.id: n.state
                      for n in servers[0].api.cluster.sorted_nodes()}
            assert all(s == "NORMAL" for s in states.values()), states
        finally:
            for s in servers:
                s.close()

    def test_concurrent_first_writes_create_one_fragment(self, tmp_path):
        """Concurrent FIRST writes into brand-new shards/views must all
        land in one Fragment per path: the old unlocked check-then-create
        handed racing writer threads distinct Fragment objects for the
        same file and silently dropped the losers' acknowledged bits
        (reproduced ~1-in-10 under the mixed-traffic load test)."""
        servers = make_cluster(tmp_path, 1, use_mesh=False)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/g", {})
            url = f"{uri(servers[0])}/index/i/query"
            for round_ in range(6):  # fresh shards each round
                base = (50 + round_) * SHARD_WIDTH
                ops = [f"Set({base + k}, g={round_})" for k in range(12)]
                out = self._concurrent(url, ops)
                assert all(r == {"results": [True]} for r in out), out
                final = req("POST", url, f"Count(Row(g={round_}))".encode())
                assert final == {"results": [12]}, (round_, final)
        finally:
            servers[0].close()

    def test_pipeline_disabled_fallback(self, tmp_path):
        servers = make_cluster(tmp_path, 1, use_mesh=False)
        try:
            seed(servers[0])
            servers[0].api.serve_pipelined = False
            url = f"{uri(servers[0])}/index/i/query"
            out = req("POST", url, b"Count(Row(f=1))")
            assert out == {"results": [24]}
            assert servers[0].api._pipeline is None
        finally:
            servers[0].close()

    def test_bad_query_in_wave_does_not_poison_wavemates(self, tmp_path):
        """One request erroring at submit time (unknown field) must fail
        ALONE; the other requests coalesced into the same wave still
        resolve correctly."""
        servers = make_cluster(tmp_path, 1, use_mesh=False)
        try:
            seed(servers[0])
            url = f"{uri(servers[0])}/index/i/query"
            queries = (["Count(Row(f=1))"] * 6
                       + ["Count(Row(nosuch=1))"]
                       + ["Count(Row(f=2))"] * 5)
            results = [None] * len(queries)
            gate = threading.Event()

            def worker(k, q):
                gate.wait(10)
                try:
                    results[k] = req("POST", url, q.encode())
                except urllib.error.HTTPError as e:
                    results[k] = ("http-error", e.code)

            threads = [threading.Thread(target=worker, args=(k, q))
                       for k, q in enumerate(queries)]
            for t in threads:
                t.start()
            gate.set()
            for t in threads:
                t.join(60)
            for q, r in zip(queries, results):
                if "nosuch" in q:
                    assert r == ("http-error", 400), r
                elif "f=1" in q:
                    assert r == {"results": [24]}, (q, r)
                else:
                    assert r == {"results": [12]}, (q, r)
        finally:
            servers[0].close()

    def test_error_propagates_through_pipeline(self, tmp_path):
        servers = make_cluster(tmp_path, 1, use_mesh=False)
        try:
            seed(servers[0])
            url = f"{uri(servers[0])}/index/i/query"
            with pytest.raises(urllib.error.HTTPError) as ei:
                req("POST", url, b"Count(Row(nosuch=1))")
            assert ei.value.code == 400
            # the pipeline survives the error and keeps serving
            out = req("POST", url, b"Count(Row(f=1))")
            assert out == {"results": [24]}
        finally:
            servers[0].close()
