"""Cluster-of-meshes topology: HTTP cluster across nodes, each node
wrapping a DistExecutor over the (virtual) device mesh.

This is the production shape wired at server.py _wire_cluster — ICI
collectives inside a node, HTTP/DCN between nodes (SURVEY.md §2.4) — and
before round 5 it was never exercised by CI: every cluster test passed
use_mesh=False. Covers query fan-out, a mid-flight resize, anti-entropy
repair feeding the mesh executor, and pipelined submit on the topology.
"""

import functools

import pytest

from cluster_helpers import join_node, make_cluster, req, seed, uri
from pilosa_tpu.parallel import dist

# Multiple in-process servers each running a DistExecutor share ONE
# forced-CPU device set; on runtimes that only ship the experimental
# shard_map, their concurrent programs deadlock in the cross-module
# all-reduce rendezvous (collective_ops_utils "stuck participant").
# Single-mesh suites (test_distributed, the mesh serving tests) are
# unaffected; only this multi-server-mesh topology must skip there.
pytestmark = pytest.mark.skipif(
    not dist.SHARD_MAP_NATIVE,
    reason="concurrent multi-server shard_map collectives deadlock on "
           "the experimental shard_map fallback (old jax CPU runtime)",
)

make_mesh_cluster = functools.partial(
    make_cluster, use_mesh=True, prefix="mnode"
)


class TestMeshClusterFanout:
    def test_every_node_wraps_a_mesh_and_agrees(self, tmp_path):
        """Each node's local executor is a DistExecutor; cross-node
        queries from every node produce the oracle answers."""
        from pilosa_tpu.parallel.dist import DistExecutor

        servers = make_mesh_cluster(tmp_path, 3)
        try:
            for s in servers:
                assert isinstance(s.api.executor.local, DistExecutor)
                assert s.api.executor.local.mesh.size == 8
            seed(servers[0])
            for s in servers:
                url = f"{uri(s)}/index/i/query"
                assert req("POST", url, b"Count(Row(f=1))") == {"results": [24]}
                assert req(
                    "POST", url, b"Count(Intersect(Row(f=1), Row(f=2)))"
                ) == {"results": [12]}
                out = req("POST", url, b"TopN(f, n=2)")
                assert out["results"][0] == [
                    {"id": 1, "count": 24}, {"id": 2, "count": 12},
                ]
                out = req("POST", url, b'Sum(Row(f=1), field="v")')
                assert out["results"][0] == {
                    "value": sum((s + 1) * 7 for s in range(6)), "count": 6,
                }
                out = req(
                    "POST", url,
                    b"GroupBy(Rows(f), having=Condition(count > 12))",
                )
                assert out["results"][0] == [
                    {"group": [{"field": "f", "rowID": 1}], "count": 24}
                ]
        finally:
            for s in servers:
                s.close()

    def test_pipelined_submit_on_mesh_cluster(self, tmp_path):
        """ClusterExecutor.submit over mesh-backed nodes: a whole stream
        submitted before any resolve, results equal eager execute."""
        servers = make_mesh_cluster(tmp_path, 2)
        try:
            seed(servers[0])
            ex = servers[1].api.executor
            queries = [
                "Count(Row(f=1))", "Union(Row(f=1), Row(f=2))",
                'Max(field="v")', "TopN(f, n=2)", "Rows(f)",
                "Count(Not(Row(f=2)))",
            ]
            want = [ex.execute("i", q)[0] for q in queries]
            defs = [ex.submit("i", q)[0] for q in queries]
            got = [d.result() for d in defs]
            from pilosa_tpu.executor.result import result_to_json

            for q, g, w in zip(queries, got, want):
                assert result_to_json(g) == result_to_json(w), q
        finally:
            for s in servers:
                s.close()


class TestMeshClusterResize:
    def test_join_resize_with_mesh_nodes(self, tmp_path):
        """A third mesh-backed node joins a live 2-node mesh cluster;
        after the resize it owns shards, holds their data, and serves
        correct cluster-wide queries."""
        servers = make_mesh_cluster(tmp_path, 2)
        try:
            seed(servers[0], n_shards=8)
            # prime both nodes' shard-universe poll caches BEFORE the
            # join: the post-cleanup re-check below must prove a node
            # still covers its formerly-local shards from its own
            # metadata when the poll cache predates the resize
            for s in servers:
                s.api.executor._all_shards("i")
            late = join_node(tmp_path, servers[0], use_mesh=True,
                             name="m9", prefix="mlate")
            servers.append(late)
            assert late.api.cluster.wait_until_normal(30)
            owned = [s for s in range(8)
                     if late.api.cluster.owns_shard("i", s)]
            assert owned, "ring should assign the new mesh node shards"
            view = late.holder.index("i").field("f").view("standard")
            for shard in owned:
                frag = view.fragment(shard)
                assert frag is not None and frag.contains(1, 100), shard
            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query",
                          b"Count(Row(f=1))")
                assert out == {"results": [32]}, s.api.cluster.local.id
            # Deterministic post-cleanup coverage (the async cleanup may
            # or may not have landed by the queries above): prime every
            # node's shard-universe poll cache, force the cleanup
            # everywhere, and re-check — a node whose formerly-local
            # fragments were just deleted must still fan out over the
            # full universe from its own metadata (regression: it lost
            # them whenever the poll cache predated the resize).
            members = sorted(servers[0].api.cluster.nodes)
            for s in servers:
                s.api.cluster.cleanup_unowned(members)
            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query",
                          b"Count(Row(f=1))")
                assert out == {"results": [32]}, (
                    "post-cleanup", s.api.cluster.local.id)
        finally:
            for s in servers:
                s.close()


class TestMeshClusterAntiEntropy:
    def test_repair_invalidates_mesh_residency(self, tmp_path):
        """Anti-entropy repair writes bits into a replica's fragments;
        a mesh executor that had already CACHED the repaired fragment's
        words on-device must serve the post-repair truth, not the stale
        resident copy."""
        servers = make_mesh_cluster(tmp_path, 2, replica_n=2)
        try:
            req("POST", f"{uri(servers[0])}/index/i", {})
            req("POST", f"{uri(servers[0])}/index/i/field/f", {})
            req("POST", f"{uri(servers[0])}/index/i/query", b"Set(1, f=1)")
            # warm BOTH nodes' mesh residency with the pre-divergence row
            for s in servers:
                out = req("POST", f"{uri(s)}/index/i/query",
                          b"Count(Row(f=1))")
                assert out == {"results": [1]}
            # diverge node0 directly, then let node1 pull the delta
            frag0 = (servers[0].holder.index("i").field("f")
                     .view("standard").fragment(0, create=True))
            frag0.set_bit(1, 999)
            repaired = servers[1].api.cluster.sync_holder()
            assert repaired["bits"] >= 1
            frag1 = (servers[1].holder.index("i").field("f")
                     .view("standard").fragment(0))
            assert frag1.contains(1, 999)
            # node1's mesh executor must see the repaired bit (query
            # routes shard 0 to a local mesh evaluation on either node)
            out = req("POST", f"{uri(servers[1])}/index/i/query",
                      b"Count(Row(f=1))")
            assert out == {"results": [2]}
            out = req("POST", f"{uri(servers[1])}/index/i/query",
                      b"Row(f=1)")
            assert out["results"][0]["columns"] == [1, 999]
        finally:
            for s in servers:
                s.close()
