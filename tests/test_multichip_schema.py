"""Tier-1 wiring for the mesh-bench record contract (ISSUE 19 sat. #2/#3):

* scripts/check_multichip_schema.py pins the MULTICHIP_r07 record shape
  (quantized wire block, reconciliation block, reduce_bytes quantized_*
  counters) — validated here against the COMMITTED record and against
  synthetic good/bad documents, plus the CLI exit codes;
* bench_suite.parse_trace_events is the hardened perfetto parse — every
  failure mode must come back as a structured ``reason`` string (never a
  crash, never a bare None), and transfer bytes are attributed only on
  device-pid lanes.
"""

import gzip
import importlib.util
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
SCRIPT = REPO / "scripts" / "check_multichip_schema.py"
RECORD = REPO / "MULTICHIP_r07.json"


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


schema = _load("check_multichip_schema", SCRIPT)


def good_record():
    return {
        "n_devices": 4, "mesh_shape": [2, 2], "n_shards": 7, "shapes": 20,
        "identical": True, "mismatches": [], "cols_per_sec": 10 ** 9,
        "row_topn_reduce_bytes": {
            "dense_equiv": 1048768, "actual": 2272, "ratio": 461.6},
        "reduce_bytes": {
            "dispatches": 12, "hier_dispatches": 12, "dense_bytes": 3072,
            "actual_bytes": 646, "intra_bytes": 2048, "row_gathers": 4,
            "row_dense_bytes": 4194304, "row_actual_bytes": 5812,
            "quantized_dispatches": 0, "quantized_actual_bytes": 0,
            "quantized_lossless_bytes": 0, "quantized_window_rows": 0,
            "quantized_candidate_rows": 0},
        "quantized": {
            "identical": True, "mismatches": [], "ranking_queries": 4,
            "wire": {"lossless_inter_bytes": 1960,
                     "quantized_inter_bytes": 804,
                     "ratio": 2.44, "lane_ratio": 4.62},
            "window": {"candidate_rows": 166, "window_rows": 28},
            "ok": True},
        "wire_reconciliation": {
            "model_bytes": 8064, "band": [0.5, 2.0],
            "device_lane": "cpu-threads", "status": "skipped",
            "reason": "no-transfer-lanes-in-trace (CPU-only host)",
            "within_band": None},
        "ok": True,
    }


def good_document():
    return {"config": "mesh", "metric": "hier_reduction_mesh_scaling",
            "meshes": [good_record()], "ok": True}


class TestSchemaChecker:
    def test_committed_record_conforms(self):
        assert RECORD.exists(), "MULTICHIP_r07.json not committed"
        doc = json.loads(RECORD.read_text())
        assert schema.check_document(doc) == []

    def test_good_synthetic_document(self):
        assert schema.check_document(good_document()) == []

    def test_measured_status_needs_measured_fields(self):
        rec = good_record()
        rec["wire_reconciliation"].update(
            {"status": "measured", "measured_bytes": 9000,
             "within_band": True})
        assert schema.check_record(rec) == []
        del rec["wire_reconciliation"]["measured_bytes"]
        assert any("measured_bytes" in p for p in schema.check_record(rec))

    def test_bad_records_are_pointed_at(self):
        rec = good_record()
        del rec["quantized"]["wire"]["lane_ratio"]
        probs = schema.check_record(rec)
        assert any("quantized.wire" in p and "lane_ratio" in p
                   for p in probs)

        rec = good_record()
        del rec["reduce_bytes"]["quantized_actual_bytes"]
        assert any("quantized_actual_bytes" in p
                   for p in schema.check_record(rec))

        rec = good_record()
        rec["wire_reconciliation"]["status"] = "maybe"
        assert any("status" in p for p in schema.check_record(rec))

        rec = good_record()
        rec["identical"] = 1  # int is not an acceptable bool stand-in
        assert any("identical" in p for p in schema.check_record(rec))

        # a degraded subprocess record ({"n_devices", "ok", "error"})
        # must FAIL validation — the committed record may not hide one
        probs = schema.check_document({
            "config": "mesh", "metric": "hier_reduction_mesh_scaling",
            "meshes": [{"n_devices": 8, "ok": False, "error": "boom"}],
            "ok": False})
        assert any("missing" in p for p in probs)

    def test_skipped_status_needs_reason(self):
        rec = good_record()
        del rec["wire_reconciliation"]["reason"]
        assert any("reason" in p for p in schema.check_record(rec))

    def test_cli_exit_codes(self, tmp_path):
        good = tmp_path / "good.json"
        good.write_text(json.dumps(good_document()))
        bad_doc = good_document()
        del bad_doc["meshes"][0]["quantized"]
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(bad_doc))
        ok = subprocess.run([sys.executable, str(SCRIPT), str(good)],
                            capture_output=True, text=True, timeout=60)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        fail = subprocess.run([sys.executable, str(SCRIPT), str(bad)],
                              capture_output=True, text=True, timeout=60)
        assert fail.returncode == 1
        assert "quantized" in fail.stdout


# ---------------------------------------------------------------------------
# parse_trace_events: synthetic perfetto traces


bench = _load("bench_suite_under_test", REPO / "bench_suite.py")


def _write_trace(tmp_path, events, name="t.trace.json.gz"):
    d = tmp_path / "plugins" / "profile" / "run"
    d.mkdir(parents=True, exist_ok=True)
    with gzip.open(d / name, "wt") as fh:
        json.dump({"traceEvents": events}, fh)


def device_trace_events():
    """A TPU-shaped trace: device process with an XLA Ops lane, one
    all-reduce op carrying profiler byte attribution, one covering
    module span on another thread (must NOT be double counted)."""
    return [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0 (chip 0)"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 2,
         "args": {"name": "XLA Modules"}},
        {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.3", "dur": 40},
        {"ph": "X", "pid": 7, "tid": 1, "name": "all-reduce.1",
         "dur": 10, "args": {"bytes_accessed": 1234}},
        {"ph": "X", "pid": 7, "tid": 2, "name": "module-span",
         "dur": 500},
    ]


class TestParseTraceEvents:
    def test_empty_dir_is_structured_skip(self, tmp_path):
        r = bench.parse_trace_events(str(tmp_path))
        assert r["ok"] is False
        assert r["reason"] == "no-trace-files"
        assert r["transfer"]["reason"] == "no-trace-files"

    def test_device_lane_attribution(self, tmp_path):
        _write_trace(tmp_path, device_trace_events())
        r = bench.parse_trace_events(str(tmp_path))
        assert r["ok"] is True
        assert r["device_lane"] == "device-ops"
        assert r["device_us"] == 50.0  # ops lane only, no module span
        assert r["transfer"] == {"ok": True, "bytes": 1234, "events": 1,
                                 "reason": None}

    def test_cpu_only_host_is_structured_skip(self, tmp_path):
        _write_trace(tmp_path, [
            {"ph": "M", "name": "process_name", "pid": 3,
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "name": "thread_name", "pid": 3, "tid": 9,
             "args": {"name": "tf_XLA_worker_0"}},
            # CPU lanes name the same fused collectives but model no
            # wire — bytes there must NOT be attributed
            {"ph": "X", "pid": 3, "tid": 9, "name": "all-reduce.0",
             "dur": 25, "args": {"bytes_accessed": 999}},
        ])
        r = bench.parse_trace_events(str(tmp_path))
        assert r["ok"] is True
        assert r["device_lane"] == "cpu-threads"
        assert r["device_us"] == 25.0
        assert r["transfer"]["ok"] is False
        assert r["transfer"]["bytes"] == 0
        assert r["transfer"]["reason"] == \
            "no-transfer-lanes-in-trace (CPU-only host)"

    def test_transfer_without_bytes_has_its_own_reason(self, tmp_path):
        ev = device_trace_events()
        del ev[4]["args"]  # the collective loses its byte attribution
        _write_trace(tmp_path, ev)
        r = bench.parse_trace_events(str(tmp_path))
        assert r["ok"] is True
        assert r["transfer"]["ok"] is False
        assert r["transfer"]["events"] == 1
        assert r["transfer"]["reason"] == \
            "transfer-events-without-byte-attribution"

    def test_corrupt_trace_is_parse_error_reason(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "run"
        d.mkdir(parents=True)
        (d / "x.trace.json.gz").write_bytes(b"not gzip at all")
        r = bench.parse_trace_events(str(tmp_path))
        assert r["ok"] is False
        assert r["reason"] == "trace-parse-errors"

    def test_byte_key_conventions(self):
        f = bench._transfer_event_bytes
        assert f({"args": {"bytes accessed": "2,048"}}) == 2048
        assert f({"args": {"bytes_transferred": 7.0}}) == 7
        assert f({"args": {"bytes": ""}}) is None
        assert f({"args": {"bytes": "n/a"}}) is None
        assert f({}) is None
