"""Durability subsystem: group-commit WAL, crash recovery, backup/restore.

Three layers (docs/TESTING.md):

1. WAL unit tests — group batching (one fsync per group of concurrent
   writers), barrier semantics, mode switches, segment rotation +
   checkpoint GC, tombstones, commit-failure propagation.
2. Torn-tail fuzz — a crash mid-append may leave a partial final
   record; recovery must drop EXACTLY that record and nothing else,
   proven at every byte offset of the final record for both the
   fragment op log and the WAL segment format.
3. The crash-recovery oracle — a real subprocess node SIGKILLed mid
   write-burst must come back with every ACKed write (group AND per-op
   modes), bit-for-bit against the client's ACK ledger; plus the
   backup → restore round trip, byte-identical.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.roaring import RoaringBitmap
from pilosa_tpu.roaring.format import encode_op, load, serialize
from pilosa_tpu.storage import Holder
from pilosa_tpu.storage.view import VIEW_STANDARD
from pilosa_tpu.storage.wal import (
    MODE_FLUSH_ONLY,
    MODE_GROUP,
    MODE_PER_OP,
    REC_OP,
    WriteAheadLog,
    encode_wal_record,
    iter_wal_records,
)


def _mk_holder(tmp_path, name="h", **kw):
    return Holder(str(tmp_path / name), **kw).open()


def _frag(holder, index="i", field="f", shard=0):
    idx = holder.index(index) or holder.create_index(index)
    fld = idx.field(field) or idx.create_field(field)
    return fld.view(VIEW_STANDARD, create=True).fragment(shard, create=True)


def _crash_copy(holder, tmp_path, name="crashed"):
    """Simulate a crash: copy the data dir while the holder is live (no
    close, no snapshot, no cache save) and reopen the copy."""
    holder.wal.barrier()
    dst = str(tmp_path / name)
    shutil.copytree(holder.data_dir, dst)
    return Holder(dst)


# --------------------------------------------------------------- WAL units


class TestGroupCommit:
    def test_one_fsync_covers_a_group_of_concurrent_writers(self, tmp_path):
        h = _mk_holder(tmp_path)
        fsyncs = []
        h.wal._fsync = lambda fd: fsyncs.append(fd) or os.fsync(fd)
        frags = [_frag(h, shard=s) for s in range(4)]
        gate = threading.Event()

        def writer(tid):
            gate.wait(10)
            for k in range(25):
                frags[tid % 4].set_bit(1, tid * 100 + k)
            h.wal.barrier()

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(30)
        m = h.wal.metrics()
        assert m["appended_ops_total"] == 200
        # the whole point: far fewer fsyncs than ops, and groups that
        # actually batched concurrent writers
        assert m["fsyncs_total"] == len(fsyncs) < 100
        assert m["group_max_ops"] > 1
        h.close()

    def test_barrier_releases_only_after_fsync(self, tmp_path):
        h = _mk_holder(tmp_path)
        fsynced = threading.Event()
        orig = h.wal._fsync

        def slow_fsync(fd):
            time.sleep(0.05)
            orig(fd)
            fsynced.set()

        h.wal._fsync = slow_fsync
        frag = _frag(h)
        frag.set_bit(1, 1)
        assert not fsynced.is_set()  # append alone must not be "durable"
        h.wal.barrier()
        assert fsynced.is_set()
        h.close()

    def test_group_mode_writes_skip_fragment_file(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.set_bit(1, 5)
        h.wal.barrier()
        with open(frag.path, "rb") as f:
            bitmap, n_ops = load(f.read())
        assert n_ops == 0 and bitmap.count() == 0  # ops live in the WAL
        h.close()
        # clean close snapshots: the file is now self-contained
        with open(frag.path, "rb") as f:
            bitmap, n_ops = load(f.read())
        assert n_ops == 0 and bitmap.count() == 1

    def test_per_op_mode_fsyncs_every_record(self, tmp_path, monkeypatch):
        calls = []
        from pilosa_tpu.storage import fragment as frag_mod

        monkeypatch.setattr(frag_mod, "wal_fsync",
                            lambda fd: calls.append(fd) or os.fsync(fd))
        h = _mk_holder(tmp_path, durability_mode=MODE_PER_OP)
        frag = _frag(h)
        before = len(calls)
        for i in range(5):
            frag.set_bit(1, i)
        assert len(calls) - before == 5
        h.close()

    def test_flush_only_mode_never_fsyncs_writes(self, tmp_path, monkeypatch):
        calls = []
        from pilosa_tpu.storage import fragment as frag_mod

        monkeypatch.setattr(frag_mod, "wal_fsync",
                            lambda fd: calls.append(fd))
        h = _mk_holder(tmp_path, durability_mode=MODE_FLUSH_ONLY)
        frag = _frag(h)
        for i in range(5):
            frag.set_bit(1, i)
        assert not calls
        assert h.wal.metrics()["fsyncs_total"] == 0
        h.wal.barrier()  # must be a free no-op outside group mode
        h.close()

    def test_invalid_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="durability"):
            Holder(str(tmp_path / "x"), durability_mode="maybe")
        from pilosa_tpu.server import ServerConfig

        with pytest.raises(ValueError, match="durability"):
            ServerConfig(durability_mode="yolo")

    def test_commit_failure_fails_the_barrier(self, tmp_path):
        h = _mk_holder(tmp_path)

        def broken(fd):
            raise OSError("disk gone")

        h.wal._fsync = broken
        frag = _frag(h)
        frag.set_bit(1, 1)
        with pytest.raises(OSError, match="wal commit failed"):
            h.wal.barrier()
        # the write path surfaces it too, instead of acking silently
        # volatile writes
        h.wal._error = None  # reset so close() can finish
        h.wal._fsync = os.fsync
        h.close()

    def test_config_knobs_roundtrip(self):
        from pilosa_tpu.server import ServerConfig

        cfg = ServerConfig.from_dict({
            "durability-mode": "per-op",
            "group-commit-max-ms": "7.5",
            "group-commit-max-ops": "64",
        })
        assert cfg.durability_mode == "per-op"
        assert cfg.group_commit_max_ms == 7.5
        assert cfg.group_commit_max_ops == 64
        d = cfg.to_dict()
        assert d["durability-mode"] == "per-op"
        assert d["group-commit-max-ms"] == 7.5
        assert d["group-commit-max-ops"] == 64
        # snake_case fallback like the sibling knobs
        assert ServerConfig.from_dict(
            {"durability_mode": "flush-only"}
        ).durability_mode == "flush-only"

    def test_segment_rotation_checkpoints_and_gcs(self, tmp_path,
                                                  monkeypatch):
        from pilosa_tpu.storage import wal as wal_mod

        monkeypatch.setattr(wal_mod, "SEGMENT_MAX_BYTES", 4096)
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        for i in range(300):
            frag.set_bit(1, i)
            if i % 50 == 49:
                h.wal.barrier()
        h.wal.barrier()
        deadline = time.time() + 10
        while time.time() < deadline:
            if (h.wal.metrics()["checkpoints_total"] > 0
                    and h.wal.metrics()["segments"] <= 2):
                break
            time.sleep(0.05)
        m = h.wal.metrics()
        assert m["checkpoints_total"] > 0, m
        assert m["segments"] <= 2, m  # rotated segments were GCed
        # the checkpoint snapshot persisted every op the GCed segments
        # held (the active segment still covers the newest tail)
        with open(frag.path, "rb") as f:
            bitmap, _ = load(f.read())
        assert bitmap.count() > 0
        h.close()
        h2 = Holder(str(tmp_path / "h")).open()
        assert (h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
                .count_row(1)) == 300
        h2.close()

    def test_keyed_write_ack_syncs_translate_log(self, tmp_path):
        """An acked keyed write's key→ID mapping must be as durable as
        its bit: IDs are implicit in translate-log append order, so a
        lost mapping would re-attribute the recovered bit to a LATER
        key."""
        from tests.cluster_helpers import make_cluster, req, uri

        (s,) = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(s)}/index/k", {"options": {"keys": True}})
            req("POST", f"{uri(s)}/index/k/field/f",
                {"options": {"keys": True}})
            req("POST", f"{uri(s)}/index/k/query",
                b'Set("alice", f="pizza")')
            assert s.holder.translate._dirty is False  # synced at ACK
        finally:
            s.close()

    def test_commit_thread_death_fails_writes_not_hangs(self, tmp_path):
        """A commit-thread failure anywhere (not just the guarded fsync)
        must surface as a write error — a silent death would wedge every
        write handler on a barrier that can never advance."""
        import urllib.error

        from tests.cluster_helpers import make_cluster, req, uri

        (s,) = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(s)}/index/i", {})
            req("POST", f"{uri(s)}/index/i/field/f", {})

            def broken(fd):
                raise OSError("disk gone")

            s.holder.wal._fsync = broken
            with pytest.raises(urllib.error.HTTPError) as err:
                req("POST", f"{uri(s)}/index/i/query", b"Set(1, f=1)")
            assert err.value.code == 500
        finally:
            s.holder.wal._error = None
            s.holder.wal._fsync = os.fsync
            s.close()

    def test_wal_metrics_exported_via_api(self, tmp_path):
        from tests.cluster_helpers import make_cluster, req, uri

        (s,) = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(s)}/index/i", {})
            req("POST", f"{uri(s)}/index/i/field/f", {})
            req("POST", f"{uri(s)}/index/i/query", b"Set(3, f=1)")
            text = req("GET", f"{uri(s)}/metrics", raw=True).decode()
            assert "wal_groups_total" in text
            assert "wal_fsyncs_total" in text
            dv = req("GET", f"{uri(s)}/debug/vars")
            assert dv["durability"]["appended_ops_total"] >= 1
            assert dv["durability"]["fsyncs_total"] >= 1
        finally:
            s.close()


class TestRecovery:
    def test_crash_recovery_replays_acked_ops(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.bulk_import(np.repeat([1, 2], 50),
                         np.arange(100, dtype=np.uint64))
        frag.set_bit(9, 99)
        frag.clear_bit(1, 0)
        val = _frag(h, field="v", shard=1)
        val.set_bit(3, 7)
        h2 = _crash_copy(h, tmp_path).open()
        f2 = h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        assert not f2.contains(1, 0)
        assert f2.contains(1, 1) and f2.contains(2, 50)
        assert f2.contains(9, 99)
        v2 = h2.index("i").field("v").view(VIEW_STANDARD).fragment(1)
        assert v2.contains(3, 7)
        # byte-level: recovered state identical to the live writer's
        assert f2.serialize_snapshot() == frag.serialize_snapshot()
        h2.close()
        h.close()

    def test_recovered_row_cache_is_recounted(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        for i in range(20):
            frag.set_bit(4, i)
        h2 = _crash_copy(h, tmp_path).open()
        f2 = h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        assert f2.top(1) == [(4, 20)]
        h2.close()
        h.close()

    def test_tombstone_blocks_resurrection_across_recovery(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.set_bit(1, 5)
        h.delete_index("i")
        frag2 = _frag(h)  # recreate same names, write different data
        frag2.set_bit(2, 6)
        h2 = _crash_copy(h, tmp_path).open()
        f2 = h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        assert not f2.contains(1, 5)  # deleted era must not come back
        assert f2.contains(2, 6)
        h2.close()
        h.close()

    def test_shard_tombstone_does_not_swallow_decimal_siblings(
            self, tmp_path):
        # shard 1's tombstone is the exact key "i/f/standard/1"; a
        # prefix match would also cover "i/f/standard/10" and drop
        # shard 10's acked-but-unsnapshotted ops on replay
        h = _mk_holder(tmp_path)
        f1 = _frag(h, shard=1)
        f10 = _frag(h, shard=10)
        f1.set_bit(1, 1)
        f10.set_bit(2, 2)
        h.index("i").field("f").view(VIEW_STANDARD).remove_fragment(1)
        h2 = _crash_copy(h, tmp_path).open()
        v2 = h2.index("i").field("f").view(VIEW_STANDARD)
        assert v2.fragment(10).contains(2, 2)  # acked write survived
        f1b = v2.fragment(1)
        assert f1b is None or not f1b.contains(1, 1)  # deleted stays dead
        h2.close()
        h.close()

    def test_tombstone_segment_outlives_pinned_older_ops(self, tmp_path):
        # the segment holding ONLY a tombstone must not GC while an
        # older segment (pinned by another fragment's uncovered ops)
        # still holds the tombstoned fragment's op records — a crash in
        # that window would replay them with no tombstone on disk and
        # resurrect the deleted shard with stale data (guaranteed by
        # oldest-first segment reclamation)
        h = _mk_holder(tmp_path)
        fa = _frag(h, shard=0)
        fb = _frag(h, shard=1)
        fa.set_bit(1, 1)
        fb.set_bit(2, 2)
        h.wal.barrier()
        h.wal._open_segment()  # close the segment holding both ops
        h.index("i").field("f").view(VIEW_STANDARD).remove_fragment(0)
        h.wal._open_segment()  # close the segment holding the tombstone
        h.wal._gc_segments()
        # shard 1's op pins segment one; the tombstone segment must
        # survive with it even though all ITS records are "covered"
        with h.wal._seg_lock:
            assert all(os.path.exists(s.path) for s in h.wal._segments)
            assert len(h.wal._segments) == 3
        h2 = _crash_copy(h, tmp_path).open()
        v2 = h2.index("i").field("f").view(VIEW_STANDARD)
        f0 = v2.fragment(0)
        assert f0 is None or not f0.contains(1, 1)  # no resurrection
        assert v2.fragment(1).contains(2, 2)
        h2.close()
        h.close()

    def test_segment_gc_is_oldest_first_suffix_preserving(self, tmp_path):
        # out-of-order reclamation breaks the suffix-replay invariant:
        # if the newer segment holding f's clear op were GC'd (f fully
        # snapshot-covered) while the older segment survives (pinned by
        # g), a crash would replay f's ADD on top of a snapshot that
        # already folded in the clear — resurrecting the cleared bit
        h = _mk_holder(tmp_path)
        f = _frag(h, shard=0)
        g = _frag(h, shard=1)
        f.set_bit(1, 5)
        g.set_bit(2, 6)  # pins segment one: never snapshotted
        h.wal.barrier()
        h.wal._open_segment()
        f.clear_bit(1, 5)
        h.wal.barrier()
        h.wal._open_segment()
        f.snapshot()  # covers BOTH of f's segments
        h.wal._gc_segments()
        with h.wal._seg_lock:
            assert len(h.wal._segments) == 3  # nothing reclaimed mid-log
        h2 = _crash_copy(h, tmp_path).open()
        v2 = h2.index("i").field("f").view(VIEW_STANDARD)
        assert not v2.fragment(0).contains(1, 5)  # the clear wins
        assert v2.fragment(1).contains(2, 6)
        h2.close()
        h.close()

    def test_recover_finishes_crashed_shard_delete(self, tmp_path):
        # remove_fragment crashing AFTER the durable tombstone but
        # BEFORE the unlinks must not leave the shard resurrected from
        # its snapshot file: recover() redoes the delete
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.set_bit(1, 5)
        frag.snapshot()  # bit durable in the fragment FILE itself
        h.wal.tombstone(frag.wal_key)
        h.wal.barrier()  # ...and remove_fragment crashes right here
        h2 = _crash_copy(h, tmp_path).open()
        v2 = h2.index("i").field("f").view(VIEW_STANDARD)
        f0 = v2.fragment(0)
        assert f0 is None or not f0.contains(1, 5)
        assert not os.path.exists(os.path.join(v2.path, "fragments", "0"))
        h2.close()
        h.close()

    def test_open_sweeps_crashed_delete_trash_dirs(self, tmp_path):
        # delete_index/delete_field rename to .trash-* before removing;
        # a crash in between must not resurrect it on the next open
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.set_bit(1, 5)
        h.close()
        os.rename(str(tmp_path / "h" / "i"),
                  str(tmp_path / "h" / ".trash-i"))
        h2 = Holder(str(tmp_path / "h")).open()
        assert h2.index("i") is None
        assert not os.path.exists(str(tmp_path / "h" / ".trash-i"))
        h2.close()

    def test_recovery_skips_ops_for_deleted_fields(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.set_bit(1, 5)
        h.index("i").delete_field("f")
        h2 = _crash_copy(h, tmp_path).open()
        assert h2.index("i").field("f") is None
        h2.close()
        h.close()

    def test_mode_switch_after_crash_still_recovers(self, tmp_path):
        h = _mk_holder(tmp_path)
        frag = _frag(h)
        frag.set_bit(1, 5)
        h2 = _crash_copy(h, tmp_path)
        # the operator reconfigured durability before the restart: the
        # group-mode WAL left by the crash must still replay
        h2.wal.configure(mode=MODE_FLUSH_ONLY)
        h2.open()
        assert (h2.index("i").field("f").view(VIEW_STANDARD)
                .fragment(0).contains(1, 5))
        h2.close()
        h.close()


# ------------------------------------------------------------- torn tails


def _fragment_file_with_ops(n_ops=3):
    """A fragment file image: snapshot of {} + n_ops add records."""
    base = RoaringBitmap()
    buf = bytearray(serialize(base))
    offsets = [len(buf)]
    rng = np.random.default_rng(5)
    ops = []
    for k in range(n_ops):
        ids = np.sort(rng.choice(1 << 18, 5 + k, replace=False)
                      .astype(np.uint64))
        ops.append(ids)
        buf.extend(encode_op(1, ids))
        offsets.append(len(buf))
    return bytes(buf), ops, offsets


class TestTornTails:
    def test_fragment_log_truncation_at_every_byte_offset(self):
        """Fuzz replay_ops with the final record truncated at EVERY byte
        offset: recovery must drop exactly the torn record — all earlier
        records intact, nothing of the torn one applied."""
        buf, ops, offsets = _fragment_file_with_ops()
        want_partial = set()
        for ids in ops[:-1]:
            want_partial.update(ids.tolist())
        full_start, full_end = offsets[-2], offsets[-1]
        for cut in range(full_start, full_end):  # every offset, incl. 0 bytes
            bitmap, n_ops = load(buf[:cut])
            assert n_ops == len(ops) - 1, f"cut at {cut}"
            assert set(bitmap.to_ids().tolist()) == want_partial, \
                f"cut at {cut}"
        # the intact file replays everything
        bitmap, n_ops = load(buf)
        assert n_ops == len(ops)

    def test_fragment_log_corrupt_final_crc_drops_only_that_record(self):
        buf, ops, offsets = _fragment_file_with_ops()
        bad = bytearray(buf)
        bad[-1] ^= 0xFF  # flip a payload byte: crc mismatch
        bitmap, n_ops = load(bytes(bad))
        assert n_ops == len(ops) - 1
        want = set()
        for ids in ops[:-1]:
            want.update(ids.tolist())
        assert set(bitmap.to_ids().tolist()) == want

    def test_fragment_log_garbage_tail_dropped(self):
        buf, ops, _ = _fragment_file_with_ops()
        bitmap, n_ops = load(buf + b"\x00garbage\xff" * 3)
        assert n_ops == len(ops)

    def test_wal_segment_truncation_at_every_byte_offset(self):
        recs = [
            encode_wal_record(REC_OP, "i/f/standard/0",
                              encode_op(1, np.arange(4, dtype=np.uint64))),
            encode_wal_record(REC_OP, "i/f/standard/1",
                              encode_op(2, np.arange(3, dtype=np.uint64))),
        ]
        buf = b"".join(recs)
        for cut in range(len(recs[0]), len(buf)):
            got = list(iter_wal_records(buf[:cut]))
            assert len(got) == 1, f"cut at {cut}"
            assert got[0][1] == "i/f/standard/0"
        assert len(list(iter_wal_records(buf))) == 2
        # corrupt crc in the tail record: dropped, first intact
        bad = bytearray(buf)
        bad[-1] ^= 0x55
        assert len(list(iter_wal_records(bytes(bad)))) == 1


# ----------------------------------------------------- subprocess oracle


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _req(method, url, body=None, timeout=30):
    data = (body if isinstance(body, (bytes, type(None)))
            else json.dumps(body).encode())
    r = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _spawn(tmp_path, name, port, mode, extra_env=None, seed_port=None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PILOSA_TPU_NAME": name,
        "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "0",
        "PILOSA_TPU_HEARTBEAT_INTERVAL": "0",
        "PILOSA_TPU_USE_MESH": "false",
        "PILOSA_TPU_DURABILITY_MODE": mode,
        **(extra_env or {}),
    }
    if seed_port is not None:
        env["PILOSA_TPU_SEEDS"] = f"http://127.0.0.1:{seed_port}"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu", "server",
         "--data-dir", str(tmp_path / name), "--bind", "127.0.0.1",
         "--port", str(port)],
        env=env, cwd=repo_root,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )
    base = f"http://127.0.0.1:{port}"
    for _ in range(240):
        if proc.poll() is not None:
            raise AssertionError(f"{name} exited rc={proc.returncode}")
        try:
            _req("GET", f"{base}/status", timeout=5)
            return proc, base
        except Exception:
            time.sleep(0.25)
    proc.terminate()
    raise AssertionError(f"{name} never served /status")


def _kill_burst_oracle(tmp_path, mode, n_writers=6, warmup_writes=30):
    """SIGKILL a subprocess node mid write-burst and verify the restart
    against the clients' ACK ledger: every acked column present, and
    nothing beyond acked ∪ in-flight (bit-exact, checked offline too)."""
    proc = None
    port = _free_port()
    try:
        proc, base = _spawn(tmp_path, f"oracle-{mode}", port, mode)
        _req("POST", f"{base}/index/i", {})
        _req("POST", f"{base}/index/i/field/f", {})
        acked: set[int] = set()
        inflight: dict[int, int] = {}  # tid -> col awaiting its ACK
        lock = threading.Lock()
        stop = threading.Event()

        def writer(tid):
            k = 0
            while not stop.is_set():
                col = tid + k * n_writers
                k += 1
                with lock:
                    inflight[tid] = col
                try:
                    out = _req("POST", f"{base}/index/i/query",
                               f"Set({col}, f=1)".encode(), timeout=10)
                except Exception:
                    return  # the kill landed mid-request
                if out == {"results": [True]}:
                    with lock:
                        acked.add(col)
                        inflight.pop(tid, None)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_writers)]
        for t in threads:
            t.start()
        # let the burst run, then kill mid-flight: no close(), no
        # snapshot, no cache save, pending groups torn arbitrarily
        deadline = time.time() + 60
        while len(acked) < warmup_writes:
            assert time.time() < deadline, (
                f"burst stalled at {len(acked)} acked writes")
            time.sleep(0.02)
        time.sleep(0.3)
        proc.kill()
        proc.wait(15)
        stop.set()
        for t in threads:
            t.join(15)
        with lock:
            acked_now = set(acked)
            maybe = set(inflight.values())
        assert len(acked_now) >= warmup_writes

        proc, base = _spawn(tmp_path, f"oracle-{mode}", port, mode)
        out = _req("POST", f"{base}/index/i/query", b"Row(f=1)",
                   timeout=60)
        got = set(out["results"][0]["columns"])
        missing = acked_now - got
        stray = got - acked_now - maybe
        assert not missing, f"{mode}: lost {len(missing)} ACKed writes"
        assert not stray, f"{mode}: {len(stray)} unexplained bits"
        # the reopened node keeps serving writes
        assert _req("POST", f"{base}/index/i/query",
                    b"Set(999999, f=2)") == {"results": [True]}
        proc.terminate()
        proc.wait(15)
        proc = None
        # offline bit-exactness: the fragment equals the acked set (plus
        # any in-flight write that happened to land) exactly
        h = Holder(str(tmp_path / f"oracle-{mode}"),
                   durability_mode=mode).open()
        try:
            frag = (h.index("i").field("f").view(VIEW_STANDARD)
                    .fragment(0))
            recovered = set((frag.row_columns(1)).tolist())
            expect = acked_now | (maybe & recovered)
            assert recovered == expect
            want_ids = np.sort(np.fromiter(
                ((1 << 20) + c for c in expect), np.uint64))
            assert serialize(RoaringBitmap.from_ids(want_ids)) == \
                serialize(RoaringBitmap.from_ids(
                    np.sort((frag.row_columns(1)
                             + np.uint64(1 << 20)))))
        finally:
            h.close()
        return acked_now
    finally:
        if proc is not None:
            proc.kill()
            proc.wait(15)


def test_sigkill_group_commit_every_acked_write_survives(tmp_path):
    _kill_burst_oracle(tmp_path, "group")


def test_sigkill_per_op_every_acked_write_survives(tmp_path):
    _kill_burst_oracle(tmp_path, "per-op")


def test_crash_then_backup_restore_round_trip(tmp_path):
    """Crash → recover → backup → restore: the restored fragments must
    be byte-identical to the recovered node's."""
    acked = _kill_burst_oracle(tmp_path, "group", warmup_writes=20)
    src_dir = str(tmp_path / "oracle-group")
    from pilosa_tpu.storage.backup import backup_holder, restore_holder

    h = Holder(src_dir).open()
    try:
        manifest = backup_holder(h, str(tmp_path / "bak"))
        assert manifest["generation"] == 1
        restore_holder(str(tmp_path / "bak"), str(tmp_path / "restored"))
        h2 = Holder(str(tmp_path / "restored")).open()
        try:
            a = (h.index("i").field("f").view(VIEW_STANDARD)
                 .fragment(0).serialize_snapshot())
            b = (h2.index("i").field("f").view(VIEW_STANDARD)
                 .fragment(0).serialize_snapshot())
            assert a == b
            got = set(h2.index("i").field("f").view(VIEW_STANDARD)
                      .fragment(0).row_columns(1).tolist())
            assert acked <= got
        finally:
            h2.close()
    finally:
        h.close()


# ------------------------------------------------------- backup/restore


class TestBackupRestore:
    def _seed(self, tmp_path):
        h = _mk_holder(tmp_path, "src")
        frag = _frag(h)
        rng = np.random.default_rng(3)
        frag.bulk_import(
            np.repeat([1, 2, 130], 300),
            rng.choice(1 << 20, 900, replace=False).astype(np.uint64),
        )
        _frag(h, field="g", shard=2).set_bit(7, 7)
        return h

    def test_round_trip_byte_identical(self, tmp_path):
        h = self._seed(tmp_path)
        h.backup(str(tmp_path / "bak"))
        from pilosa_tpu.storage.backup import restore_holder

        restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst"))
        h2 = Holder(str(tmp_path / "dst")).open()
        for iname, idx in h.indexes.items():
            for fname, fld in idx.fields.items():
                for vname, view in fld.views.items():
                    for shard, frag in view.fragments.items():
                        other = (h2.index(iname).field(fname)
                                 .view(vname).fragment(shard))
                        assert other is not None, (iname, fname, shard)
                        assert (other.serialize_snapshot()
                                == frag.serialize_snapshot())
        h2.close()
        h.close()

    def test_incremental_generation_writes_only_changed_blocks(
            self, tmp_path):
        h = self._seed(tmp_path)
        m1 = h.backup(str(tmp_path / "bak"))
        frag = _frag(h)
        frag.set_bit(1, 12345)  # touches ONE checksum block
        m2 = h.backup(str(tmp_path / "bak"))
        assert m2["generation"] == 2
        assert m2["newBlobs"] == 1, m2  # only the changed block shipped
        from pilosa_tpu.storage.backup import restore_holder

        restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst1"),
                       generation=1)
        restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst2"),
                       generation=2)
        h1 = Holder(str(tmp_path / "dst1")).open()
        h2 = Holder(str(tmp_path / "dst2")).open()
        f1 = h1.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        f2 = h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        assert not f1.contains(1, 12345)
        assert f2.contains(1, 12345)
        h1.close()
        h2.close()
        assert m1["newBlobs"] > 1
        h.close()

    def test_restore_refuses_keyed_index_from_fragments_scope(
            self, tmp_path):
        # a live --host backup has no translate log: restoring a keyed
        # index from one would silently re-attribute every bit
        from pilosa_tpu.storage.backup import (
            _finish_generation,
            restore_holder,
        )

        _finish_generation(str(tmp_path / "bak"), {
            "generation": 1,
            "scope": "fragments",
            "indexes": {"k": {"options": {"keys": True}, "fields": {}}},
            "fragments": {},
            "files": {},
        })
        with pytest.raises(ValueError, match="key-translation"):
            restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst"))

    def test_corrupt_blob_fails_restore_loudly(self, tmp_path):
        h = self._seed(tmp_path)
        m = h.backup(str(tmp_path / "bak"))
        h.close()
        digest = m["fragments"]["i/f/standard/0"][0][1]
        blob = tmp_path / "bak" / "blobs" / digest
        import zlib

        payload = bytearray(zlib.decompress(blob.read_bytes()))
        payload[-1] ^= 0xFF
        blob.write_bytes(zlib.compress(bytes(payload)))
        from pilosa_tpu.storage.backup import restore_holder

        with pytest.raises(ValueError, match="verification"):
            restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst"))

    def test_corrupt_blob_compression_fails_restore_cleanly(self,
                                                            tmp_path):
        """Bit rot in the compressed stream itself (not just the
        payload) must surface as the verification ValueError the CLI
        reports — never a raw zlib traceback."""
        h = self._seed(tmp_path)
        m = h.backup(str(tmp_path / "bak"))
        h.close()
        digest = m["fragments"]["i/f/standard/0"][0][1]
        blob = tmp_path / "bak" / "blobs" / digest
        blob.write_bytes(blob.read_bytes()[: 10])  # truncated stream
        from pilosa_tpu.storage.backup import restore_holder

        with pytest.raises(ValueError, match="verification"):
            restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst"))

    def test_cli_backup_rejects_missing_data_dir(self, tmp_path, capsys):
        from pilosa_tpu.cli import main

        assert main(["backup", "-d", str(tmp_path / "typo"),
                     "-o", str(tmp_path / "bak")]) == 1
        assert "no data dir" in capsys.readouterr().err
        assert not (tmp_path / "bak").exists()

    def test_restore_refuses_nonempty_target(self, tmp_path):
        h = self._seed(tmp_path)
        h.backup(str(tmp_path / "bak"))
        h.close()
        tgt = tmp_path / "dst"
        tgt.mkdir()
        (tgt / "junk").write_text("x")
        from pilosa_tpu.storage.backup import restore_holder

        with pytest.raises(ValueError, match="not empty"):
            restore_holder(str(tmp_path / "bak"), str(tgt))

    def test_cli_backup_restore_verbs(self, tmp_path, capsys):
        from pilosa_tpu.cli import main

        h = self._seed(tmp_path)
        h.close()
        src = str(tmp_path / "src")
        bak = str(tmp_path / "bak")
        assert main(["backup", "-d", src, "-o", bak]) == 0
        assert "generation 1" in capsys.readouterr().out
        assert main(["backup", "-d", src, "-o", bak]) == 0
        assert "generation 2" in capsys.readouterr().out
        assert main(["restore", "-d", str(tmp_path / "dst"), "-i", bak,
                     "--generation", "1"]) == 0
        assert "digest-verified" in capsys.readouterr().out
        h1 = Holder(str(tmp_path / "dst")).open()
        h2 = Holder(src).open()
        a = h1.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        b = h2.index("i").field("f").view(VIEW_STANDARD).fragment(0)
        assert a.serialize_snapshot() == b.serialize_snapshot()
        h1.close()
        h2.close()
        # legacy tar path still works
        assert main(["backup", "-d", src, "-o",
                     str(tmp_path / "legacy.tar.gz")]) == 0
        assert main(["restore", "-d", str(tmp_path / "dst-tar"), "-i",
                     str(tmp_path / "legacy.tar.gz")]) == 0

    def test_live_http_backup_rides_sync_wire(self, tmp_path):
        from tests.cluster_helpers import make_cluster, req, uri

        (s,) = make_cluster(tmp_path, 1)
        try:
            req("POST", f"{uri(s)}/index/i", {})
            req("POST", f"{uri(s)}/index/i/field/f", {})
            cols = [k * 97 for k in range(50)]
            req("POST", f"{uri(s)}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            from pilosa_tpu.storage.backup import (
                backup_from_host,
                restore_holder,
            )

            m = backup_from_host(uri(s), str(tmp_path / "bak"))
            assert m["scope"] == "fragments"
            assert m["fragments"]
            restore_holder(str(tmp_path / "bak"), str(tmp_path / "dst"))
            h = Holder(str(tmp_path / "dst")).open()
            frag = h.index("i").field("f").view(VIEW_STANDARD).fragment(0)
            live = (s.holder.index("i").field("f").view(VIEW_STANDARD)
                    .fragment(0))
            assert frag.serialize_snapshot() == live.serialize_snapshot()
            h.close()
        finally:
            s.close()


# --------------------------------------------------- rolling upgrade drill


@pytest.mark.slow
def test_rolling_upgrade_drill_zero_lost_acked_writes(tmp_path):
    """Stretch drill: a 3-node replica-2 cluster under a write workload
    has one node 'upgraded' (stopped and relaunched — the PR-4
    mixed-version machinery already proves the wire survives version
    skew) while writers keep acking through the other nodes. Zero acked
    writes may be lost."""
    procs = {}
    ports = {n: _free_port() for n in ("u0", "u1", "u2")}
    bases = {}
    drill_env = {"PILOSA_TPU_REPLICA_N": "2",
                 "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "2"}
    try:
        seed = None
        for name in ("u0", "u1", "u2"):
            p, b = _spawn(tmp_path, name, ports[name], "group",
                          extra_env=drill_env, seed_port=seed)
            procs[name], bases[name] = p, b
            seed = ports["u0"]
        for b in bases.values():
            deadline = time.time() + 30
            while time.time() < deadline:
                nodes = {n["id"] for n in
                         _req("GET", f"{b}/status")["nodes"]}
                if nodes == {"u0", "u1", "u2"}:
                    break
                time.sleep(0.2)
            assert nodes == {"u0", "u1", "u2"}
        _req("POST", f"{bases['u0']}/index/i", {})
        _req("POST", f"{bases['u0']}/index/i/field/f", {})
        acked: set[int] = set()
        lock = threading.Lock()
        stop = threading.Event()

        def writer(tid):
            # writes round-robin the SURVIVING nodes (u0/u2) so the
            # upgrade window can't refuse the workload; a write that
            # errors is simply not in the ledger (the oracle is about
            # ACKED writes only)
            targets = [bases["u0"], bases["u2"]]
            k = 0
            while not stop.is_set():
                col = tid + k * 4
                k += 1
                try:
                    out = _req("POST",
                               f"{targets[k % 2]}/index/i/query",
                               f"Set({col}, f=1)".encode(), timeout=15)
                    if out == {"results": [True]}:
                        with lock:
                            acked.add(col)
                except Exception:
                    pass
                time.sleep(0.005)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        deadline = time.time() + 90
        while len(acked) < 40:
            assert time.time() < deadline, (
                f"drill stalled at {len(acked)} acked writes")
            time.sleep(0.05)
        # "upgrade" u1: stop, relaunch, wait for rejoin — mid-workload
        procs["u1"].terminate()
        procs["u1"].wait(20)
        p, b = _spawn(tmp_path, "u1", ports["u1"], "group",
                      extra_env=drill_env, seed_port=ports["u0"])
        procs["u1"], bases["u1"] = p, b
        deadline = time.time() + 60
        while time.time() < deadline:
            if _req("GET", f"{b}/status")["state"] == "NORMAL":
                break
            time.sleep(0.25)
        deadline = time.time() + 120
        while len(acked) < 120:
            assert time.time() < deadline, (
                f"drill stalled at {len(acked)} acked writes post-upgrade")
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(30)
        with lock:
            ledger = set(acked)
        # the 2 s anti-entropy ticker heals any replica the upgrade
        # window skipped; every node must converge on the full ledger
        for name, b in bases.items():
            deadline = time.time() + 60
            missing = ledger
            while time.time() < deadline:
                out = _req("POST", f"{b}/index/i/query", b"Row(f=1)",
                           timeout=60)
                missing = ledger - set(out["results"][0]["columns"])
                if not missing:
                    break
                time.sleep(1.0)
            assert not missing, (
                f"{name}: lost {len(missing)} acked writes after "
                "rolling upgrade"
            )
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(15)
            except subprocess.TimeoutExpired:
                p.kill()
