"""GroupBy aggregate=Sum and Options columnAttrs/excludeColumns tests."""

import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import PQLError
from pilosa_tpu.storage import FieldOptions, Holder


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    yield holder, Executor(holder)
    holder.close()


def seed(holder):
    idx = holder.create_index("i")
    f = idx.create_field("f")
    amount = idx.create_field("amount", FieldOptions(type="int", min=-10, max=100))
    values = {0: 5, 1: 10, 2: -10, 3: 100, 4: 7}
    rows = {1: [0, 1, 2], 2: [3, 4]}
    for row, cols in rows.items():
        for c in cols:
            f.set_bit(row, c)
    for col, v in values.items():
        amount.set_value(col, v)
    idx.mark_columns_exist(sorted(values))
    return idx, rows, values


class TestGroupByAggregate:
    def test_sum_per_group(self, env):
        holder, ex = env
        _, rows, values = seed(holder)
        (groups,) = ex.execute(
            "i", 'GroupBy(Rows(f), aggregate=Sum(field="amount"))'
        )
        got = {g.group[0]["rowID"]: (g.count, g.sum) for g in groups}
        assert got[1] == (3, 5 + 10 - 10)
        assert got[2] == (2, 107)
        assert groups[0].to_json()["sum"] == 5

    def test_sum_with_filter(self, env):
        holder, ex = env
        seed(holder)
        (groups,) = ex.execute(
            "i",
            'GroupBy(Rows(f), filter=Row(amount > 6), aggregate=Sum(field="amount"))',
        )
        got = {g.group[0]["rowID"]: (g.count, g.sum) for g in groups}
        assert got[1] == (1, 10)
        assert got[2] == (2, 107)

    def test_aggregate_requires_int_field(self, env):
        holder, ex = env
        seed(holder)
        with pytest.raises(PQLError):
            ex.execute("i", 'GroupBy(Rows(f), aggregate=Sum(field="f"))')


class TestOptions:
    def test_column_attrs(self, env):
        holder, ex = env
        idx, rows, _ = seed(holder)
        idx.column_attrs.set_attrs(0, {"city": "sf"})
        idx.column_attrs.set_attrs(2, {"city": "nyc"})
        (res,) = ex.execute("i", "Options(Row(f=1), columnAttrs=true)")
        assert res.column_attrs == [
            {"id": 0, "attrs": {"city": "sf"}},
            {"id": 2, "attrs": {"city": "nyc"}},
        ]
        assert res.to_json()["columnAttrs"] == res.column_attrs

    def test_exclude_columns_keeps_attrs(self, env):
        holder, ex = env
        idx, _, _ = seed(holder)
        idx.field("f").row_attrs.set_attrs(1, {"label": "x"})
        (res,) = ex.execute("i", "Options(Row(f=1), excludeColumns=true)")
        assert res.columns().size == 0
        assert res.attrs == {"label": "x"}


class TestGroupByKeyedRows:
    def test_keyed_dimension_emits_row_key(self, env):
        holder, ex = env
        idx = holder.create_index("k")
        lang = idx.create_field("lang", FieldOptions(keys=True))
        plain = idx.create_field("plain")
        for key, cols in {"go": [0, 1], "py": [1, 2]}.items():
            for c in cols:
                ex.execute("k", f'Set({c}, lang="{key}")')
        for c in range(3):
            plain.set_bit(5, c)
        (groups,) = ex.execute("k", "GroupBy(Rows(lang), Rows(plain))")
        got = {
            (g.group[0].get("rowKey"), g.group[1].get("rowID")): g.count
            for g in groups
        }
        assert got == {("go", 5): 2, ("py", 5): 2}
        assert all("rowKey" not in g.group[1] for g in groups)
