"""Cluster maintenance tests: attr sync, translate tailing, node-leave
resize, statsd emission."""

import socket

import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH
from tests.test_cluster import make_cluster, req, uri


def test_attr_sync_between_nodes(tmp_path):
    servers = make_cluster(tmp_path, 2)
    try:
        req("POST", f"{uri(servers[0])}/index/i", {})
        req("POST", f"{uri(servers[0])}/index/i/field/f", {})
        # attrs written directly on node0's stores only (diverged state)
        servers[0].holder.index("i").field("f").row_attrs.set_attrs(3, {"a": 1})
        servers[0].holder.index("i").column_attrs.set_attrs(9, {"b": 2})
        repaired = servers[1].api.cluster.sync_holder()
        assert repaired["attr_blocks"] >= 2
        assert servers[1].holder.index("i").field("f").row_attrs.attrs(3) == {"a": 1}
        assert servers[1].holder.index("i").column_attrs.attrs(9) == {"b": 2}
    finally:
        for s in servers:
            s.close()


def test_translate_tailing(tmp_path):
    servers = make_cluster(tmp_path, 2)
    try:
        # keyed writes translate on the coordinator; the replica's local
        # store learns the assignments by tailing the log
        req("POST", f"{uri(servers[0])}/index/users",
            {"options": {"keys": True}})
        req("POST", f"{uri(servers[0])}/index/users/field/likes",
            {"options": {"keys": True}})
        coord_id = servers[0].api.cluster.coordinator.id
        coord = next(s for s in servers if s.api.cluster.local.id == coord_id)
        replica = next(s for s in servers if s is not coord)
        req("POST", f"{uri(coord)}/index/users/query",
            b'Set("alice", likes="pizza")')
        replica.api.cluster.sync_translate()
        from pilosa_tpu.storage.translate import column_namespace, row_namespace

        # replica's local store mirrors the coordinator's assignments
        # (either tailed now or mirrored during the routed write)
        assert replica.holder.translate.translate(
            column_namespace("users"), ["alice"]
        ) == [0]
        assert replica.holder.translate.translate(
            row_namespace("users", "likes"), ["pizza"]
        ) == [0]
        # keyed reads work from the replica
        out = req("POST", f"{uri(replica)}/index/users/query",
                  b'Row(likes="pizza")')
        assert out["results"][0]["keys"] == ["alice"]
    finally:
        for s in servers:
            s.close()


def test_node_leave_triggers_reown(tmp_path):
    servers = make_cluster(tmp_path, 3, replica_n=2)
    try:
        req("POST", f"{uri(servers[0])}/index/i", {})
        req("POST", f"{uri(servers[0])}/index/i/field/f", {})
        cols = [s * SHARD_WIDTH + 2 for s in range(8)]
        req("POST", f"{uri(servers[0])}/index/i/field/f/import",
            {"rows": [1] * len(cols), "columns": cols})
        # node 2 leaves gracefully
        leaver = servers[2]
        leaver.api.cluster.leave()
        for s in servers[:2]:
            assert "n2" not in {
                n["id"] for n in req("GET", f"{uri(s)}/status")["nodes"]
            }
        leaver.close()
        # all data still queryable from the survivors
        out = req("POST", f"{uri(servers[0])}/index/i/query", b"Count(Row(f=1))")
        assert out["results"] == [8]
        out = req("POST", f"{uri(servers[1])}/index/i/query", b"Count(Row(f=1))")
        assert out["results"] == [8]
    finally:
        for s in servers[:2]:
            s.close()


def test_statsd_datagrams():
    from pilosa_tpu.utils.stats import StatsdStatsClient

    sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.settimeout(2)
    port = sink.getsockname()[1]
    client = StatsdStatsClient("127.0.0.1", port)
    client.count("queries", 1, {"call": "Count"})
    client.gauge("resident_rows", 42)
    client.timing("query", 0.005)
    got = {sink.recv(1024).decode() for _ in range(3)}
    assert "pilosa_tpu.queries:1|c|#call:Count" in got
    assert "pilosa_tpu.resident_rows:42|g" in got
    assert any(g.startswith("pilosa_tpu.query:5") and g.endswith("|ms") for g in got)
    # in-memory registry still fed
    assert "queries" in client.prometheus_text()
    sink.close()


def test_block_repair_is_binary_and_compact(tmp_path):
    """Anti-entropy block repair moves roaring bytes, not JSON int lists:
    a dense 100-row block transfers ~O(bitmap bytes) (VERDICT r1 #6)."""
    import numpy as np

    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        req("POST", f"{uri(servers[0])}/index/i", {})
        req("POST", f"{uri(servers[0])}/index/i/field/f", {})
        # diverged dense state written directly on node0's storage only:
        # 20 rows at 50% container density in checksum block 0
        f0 = servers[0].holder.index("i").field("f")
        frag0 = f0.view("standard", create=True).fragment(0, create=True)
        rng = np.random.default_rng(5)
        per_row = 30000
        rows = np.repeat(np.arange(20, dtype=np.uint64), per_row)
        poss = np.concatenate([
            rng.choice(65536, per_row, replace=False).astype(np.uint64)
            for _ in range(20)
        ])
        frag0.bulk_import(rows, poss)
        n_bits = frag0.count()
        assert n_bits == 20 * per_row

        # the other node must own shard 0 too (replica_n=2 in make_cluster)
        from pilosa_tpu.parallel.client import InternalClient

        client = InternalClient()
        raw = client._call(
            "GET",
            f"{uri(servers[0])}/internal/fragment/block/data"
            "?index=i&field=f&view=standard&shard=0&block=0",
            raw=True,
        )
        # dense data: roaring bitmap containers ~= bits/8 bytes; the old
        # JSON int lists were ~20 bytes per bit
        assert len(raw) < 0.5 * n_bits  # < 0.5 byte/bit on the wire

        repaired = servers[1].api.cluster.sync_holder()
        assert repaired["bits"] == n_bits
        f1 = servers[1].holder.index("i").field("f")
        frag1 = f1.view("standard").fragment(0)
        assert frag1.count() == n_bits
        assert frag1.blocks() == frag0.blocks()
    finally:
        for s in servers:
            s.close()
