"""Anti-entropy & resize data-plane fast path (docs/OPERATIONS.md):

- batched sync manifests (one RTT diffs a whole index against a peer)
  and multi-block deltas, byte-identical to the per-fragment r5 path;
- the RTT-count oracle (N fragments diffed in ≤ 2 fragment-sync RTTs
  per peer);
- compression negotiation + identity fallback on fragment/delta bodies;
- token-bucket pacer bounds (rate, inflight, the paced-sleep counter);
- conflict-aware merge rules (mutex/BSI) preserved through the new path;
- mixed-version cluster: one node forced JSON-only AND old-wire under a
  randomized workload (VERDICT r5 Next #5);
- a ≥30-min mixed read+write+churn+repair soak with flat-RSS /
  flat-residency oracles behind the ``slow`` marker (VERDICT Next #4).
"""

import os
import threading
import time

import numpy as np
import pytest

from cluster_helpers import make_cluster, req, uri
from pilosa_tpu.parallel.pacer import RepairPacer
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.wire.serializer import (
    decode_block_frames,
    encode_block_frames,
)


def _diverge(server, field="f", shards=(0,), rows=3, bits=200, seed=5,
             index="i"):
    """Write extra bits straight into one node's storage (replication
    bypassed) — the seeded divergence anti-entropy must heal."""
    rng = np.random.default_rng(seed)
    fld = server.holder.index(index).field(field)
    total = 0
    for shard in shards:
        frag = fld.view("standard", create=True).fragment(
            shard, create=True
        )
        r = np.repeat(np.arange(rows, dtype=np.uint64), bits)
        p = np.concatenate([
            rng.choice(SHARD_WIDTH, bits, replace=False).astype(np.uint64)
            for _ in range(rows)
        ])
        before = frag.count()
        frag.bulk_import(r, p)
        total += frag.count() - before
    return total


def _seed_schema(node0, with_index=True):
    if with_index:
        req("POST", f"{uri(node0)}/index/i",
            {"options": {"trackExistence": False}})
    req("POST", f"{uri(node0)}/index/i/field/f", {})


# ---------------------------------------------------------------- framing


def test_block_frame_roundtrip():
    payloads = [b"", b"x", b"roaring" * 100, bytes(range(256))]
    data = encode_block_frames(payloads)
    assert decode_block_frames(data) == payloads
    assert decode_block_frames(b"") == []


def test_block_frame_truncation_raises():
    data = encode_block_frames([b"abcdef", b"ghi"])
    with pytest.raises(ValueError):
        decode_block_frames(data[:-1])  # torn payload
    with pytest.raises(ValueError):
        decode_block_frames(data + b"\x00\x00")  # torn header


# ------------------------------------------------------- manifest + deltas


def test_manifest_matches_per_fragment_blocks(tmp_path):
    """The batched manifest is exactly the union of the per-fragment
    blocks GETs it replaces (same checksums, same inventory)."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        _diverge(servers[0], shards=(0, 2, 5), seed=7)
        client = servers[1].api.cluster.client
        manifest = dict(
            ((f, v, s), blocks)
            for f, v, s, blocks in client.sync_manifest(uri(servers[0]), "i")
        )
        f0 = servers[0].holder.index("i").field("f")
        for shard in (0, 2, 5):
            per_fragment = client.fragment_blocks(
                uri(servers[0]), "i", "f", "standard", shard
            )
            assert manifest[("f", "standard", shard)] == per_fragment
            frag = f0.view("standard").fragment(shard)
            assert per_fragment == frag.blocks()
    finally:
        for s in servers:
            s.close()


def test_sync_blocks_multi_fragment_delta(tmp_path):
    """One POST returns every wanted block across several fragments, in
    flattened request order, as parsed bitmaps matching block_ids."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        _diverge(servers[0], shards=(0, 1), rows=250, bits=20, seed=9)
        f0 = servers[0].holder.index("i").field("f")
        client = servers[1].api.cluster.client
        # rows 0..249 span checksum blocks 0-2 (100 rows per block)
        want = [("f", "standard", 0, [0, 1, 2]),
                ("f", "standard", 1, [0, 2])]
        bitmaps = client.sync_blocks(uri(servers[0]), "i", want)
        assert len(bitmaps) == 5
        i = 0
        for field, view, shard, blocks in want:
            frag = f0.view(view).fragment(shard)
            for block in blocks:
                assert (bitmaps[i].to_ids().tolist()
                        == frag.block_ids(block).tolist()), (shard, block)
                i += 1
    finally:
        for s in servers:
            s.close()


def _legacy_mode(server, peer_uris):
    """Force the r5 per-fragment path against the given peers (the
    old-wire fallback): no manifest/delta routes, serial pass."""
    server.api.cluster.sync_workers = 1
    for peer in peer_uris:
        server.api.cluster.client._no_manifest_peers.add(peer)


def test_fastpath_byte_identical_to_legacy(tmp_path):
    """The correctness bar of the tentpole: the same seeded divergence
    repaired via the manifest/delta fast path and via the per-fragment
    legacy path produces byte-identical fragments."""
    snaps = {}
    for mode in ("fast", "legacy"):
        servers = make_cluster(tmp_path, 2, replica_n=2, prefix=mode)
        try:
            _seed_schema(servers[0])
            cols = [s * SHARD_WIDTH + 7 * c for s in range(4)
                    for c in range(30)]
            req("POST", f"{uri(servers[0])}/index/i/field/f/import",
                {"rows": [1] * len(cols), "columns": cols})
            added = _diverge(servers[0], shards=(0, 1, 3), rows=120,
                             bits=50, seed=11)
            if mode == "legacy":
                _legacy_mode(servers[1], [uri(servers[0])])
            repaired = servers[1].api.cluster.sync_holder()
            assert repaired["bits"] == added, mode
            f1 = servers[1].holder.index("i").field("f")
            f0 = servers[0].holder.index("i").field("f")
            snaps[mode] = [
                f1.view("standard").fragment(s).serialize_snapshot()
                for s in range(4)
            ]
            for s in range(4):
                assert (f1.view("standard").fragment(s).blocks()
                        == f0.view("standard").fragment(s).blocks()), s
        finally:
            for s in servers:
                s.close()
    assert snaps["fast"] == snaps["legacy"]


def test_rtt_count_oracle(tmp_path):
    """N fragments diff (and repair) in ≤ 2 fragment-sync RTTs per peer:
    one manifest GET + at most one multi-block delta POST — against the
    legacy path's 1 catalog + N blocks GETs + K block-data GETs."""
    n_shards = 12
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        cols = [s * SHARD_WIDTH + 3 * c
                for s in range(n_shards) for c in range(20)]
        req("POST", f"{uri(servers[0])}/index/i/field/f/import",
            {"rows": [1] * len(cols), "columns": cols})
        _diverge(servers[0], shards=(0, 4, 9), seed=13)

        sync_urls = []
        pool = servers[1].api.cluster.client.pool
        real = pool.request

        def counting(method, url, body=None, headers=None, timeout=None):
            if "/internal/sync/" in url or "/internal/fragment" in url:
                sync_urls.append(url)
            return real(method, url, body=body, headers=headers,
                        timeout=timeout)

        pool.request = counting
        try:
            repaired = servers[1].api.cluster.sync_holder()
        finally:
            pool.request = real
        assert repaired["bits"] > 0
        # one manifest + one delta POST per divergent fragment, and the
        # DIFF of all 12 fragments costs exactly the manifest: ≤ 2
        # fragment-sync RTTs per (divergence-free peer would be 1)
        manifests = [u for u in sync_urls if "/sync/manifest" in u]
        deltas = [u for u in sync_urls if "/sync/blocks" in u]
        legacy_style = [u for u in sync_urls if "/internal/fragment" in u]
        assert len(manifests) == 1
        assert 1 <= len(deltas) <= 3  # one per divergent fragment
        assert not legacy_style  # the per-fragment path never fired
    finally:
        for s in servers:
            s.close()


def test_no_divergence_pass_is_one_rtt_and_skips_recompute(tmp_path):
    """Zero divergence: the whole index diffs in ONE manifest RTT, and
    no fragment recomputes its checksum set after a peer that repaired
    nothing (the r5 pass re-hashed after every peer)."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        cols = [s * SHARD_WIDTH + c for s in range(6) for c in range(40)]
        req("POST", f"{uri(servers[0])}/index/i/field/f/import",
            {"rows": [1] * len(cols), "columns": cols})
        # settle both replicas, then instrument node1's fragments
        servers[1].api.cluster.sync_holder()
        f1 = servers[1].holder.index("i").field("f")
        calls = {"blocks": 0}
        frags = [f1.view("standard").fragment(s) for s in range(6)]
        originals = [f.blocks for f in frags]

        def wrap(frag, orig):
            def counted():
                calls["blocks"] += 1
                return orig()
            return counted

        for frag, orig in zip(frags, originals):
            frag.blocks = wrap(frag, orig)
        sync_urls = []
        pool = servers[1].api.cluster.client.pool
        real = pool.request

        def counting(method, url, body=None, headers=None, timeout=None):
            if "/internal/sync/" in url or "/internal/fragment" in url:
                sync_urls.append(url)
            return real(method, url, body=body, headers=headers,
                        timeout=timeout)

        pool.request = counting
        try:
            repaired = servers[1].api.cluster.sync_holder()
        finally:
            pool.request = real
            for frag, orig in zip(frags, originals):
                frag.blocks = orig
        assert repaired["bits"] == 0
        assert len(sync_urls) == 1 and "/sync/manifest" in sync_urls[0]
        # exactly one local checksum walk per fragment, zero post-peer
        # recomputes (and the walk itself is served by the memo)
        assert calls["blocks"] == len(frags)
    finally:
        for s in servers:
            s.close()


def test_blocks_memo_invalidates_on_write(tmp_path):
    """fragment.blocks() memoizes against the mutation counter: same
    object until a write, fresh (and correct) after."""
    from pilosa_tpu.storage import Holder

    holder = Holder(str(tmp_path / "m")).open()
    try:
        frag = (holder.create_index("i").create_field("f")
                .view("standard", create=True).fragment(0, create=True))
        frag.bulk_import(np.array([1, 1], np.uint64),
                         np.array([5, 9], np.uint64))
        first = frag.blocks()
        assert frag.blocks() is first  # memo hit
        frag.set_bit(1, 700)
        second = frag.blocks()
        assert second is not first
        assert second != first
    finally:
        holder.close()


def test_unknown_index_answers_empty_not_404(tmp_path):
    """A peer lagging on a schema broadcast answers an EMPTY manifest /
    empty delta payloads for an index it doesn't know — NOT a 404, which
    the client would misread as 'route missing' and permanently demote
    the peer to the per-fragment legacy path."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        client = servers[1].api.cluster.client
        assert client.sync_manifest(uri(servers[0]), "nope") == []
        assert client.supports_sync_manifest(uri(servers[0]))
        bitmaps = client.sync_blocks(
            uri(servers[0]), "nope", [("f", "standard", 0, [0, 1])]
        )
        assert [bm.count() for bm in bitmaps] == [0, 0]
        assert client.supports_sync_manifest(uri(servers[0]))
    finally:
        for s in servers:
            s.close()


def test_malformed_manifest_does_not_abort_pass(tmp_path):
    """One peer answering a malformed 200 manifest is skipped for the
    pass (logged), not allowed to abort repair against every peer."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        added = _diverge(servers[0], shards=(0,), seed=31)
        client = servers[1].api.cluster.client
        real = client.sync_manifest
        calls = {"n": 0}

        def flaky(uri_, index):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("truncated body")  # not a ClientError
            return real(uri_, index)

        client.sync_manifest = flaky
        try:
            first = servers[1].api.cluster.sync_holder()
            second = servers[1].api.cluster.sync_holder()
        finally:
            client.sync_manifest = real
        assert first["bits"] == 0  # peer skipped, pass completed
        assert second["bits"] == added  # next pass heals
    finally:
        for s in servers:
            s.close()


# ------------------------------------------------------------- compression


def test_compression_negotiation_and_fallback(tmp_path):
    """Fragment payloads ride zlib Content-Encoding when (and only when)
    the client advertises it; bytes decode identically either way, and a
    plain client (no Accept-Encoding) gets identity bytes."""
    import urllib.request
    import zlib

    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        _diverge(servers[0], shards=(0,), rows=40, bits=4000, seed=3)
        frag = (servers[0].holder.index("i").field("f")
                .view("standard").fragment(0))
        plain = frag.serialize_snapshot()
        client = servers[1].api.cluster.client
        url = (f"{uri(servers[0])}/internal/fragment/data"
               "?index=i&field=f&view=standard&shard=0")

        client.compress_repair = True
        resp = client._call("GET", url, headers=client._repair_headers(),
                            want_response=True)
        assert resp.headers.get("Content-Encoding") == "deflate"
        assert len(resp.data) < len(plain)
        assert zlib.decompress(resp.data) == plain
        # the public helper does the decode
        assert client.fragment_data(
            uri(servers[0]), "i", "f", "standard", 0) == plain

        client.compress_repair = False  # knob off: identity on the wire
        resp = client._call("GET", url, headers=client._repair_headers(),
                            want_response=True)
        assert resp.headers.get("Content-Encoding") is None
        assert resp.data == plain

        # a plain stdlib client (no Accept-Encoding) gets identity bytes
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.read() == plain

        # delta payloads negotiate the same way
        client.compress_repair = True
        bitmaps = client.sync_blocks(
            uri(servers[0]), "i", [("f", "standard", 0, [0])]
        )
        assert bitmaps[0].to_ids().tolist() == frag.block_ids(0).tolist()
    finally:
        for s in servers:
            s.close()


def test_json_only_peer_still_syncs(tmp_path):
    """Protobuf-less negotiation (the 406 fallback class): a peer forced
    JSON-only for manifests/deltas repairs identically."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        added = _diverge(servers[0], shards=(0, 2), seed=21)
        servers[1].api.cluster.client._json_only_peers.add(uri(servers[0]))
        repaired = servers[1].api.cluster.sync_holder()
        assert repaired["bits"] == added
        f0 = servers[0].holder.index("i").field("f")
        f1 = servers[1].holder.index("i").field("f")
        for s in (0, 2):
            assert (f1.view("standard").fragment(s).blocks()
                    == f0.view("standard").fragment(s).blocks())
    finally:
        for s in servers:
            s.close()


# ------------------------------------------------------------------- pacer


def test_pacer_rate_bounds_throughput():
    from pilosa_tpu.utils.stats import StatsClient

    stats = StatsClient()
    pacer = RepairPacer(max_bytes_per_sec=2_000_000, stats=stats)
    t0 = time.perf_counter()
    total = 0
    for _ in range(40):
        pacer.consume(65536)
        total += 65536
    elapsed = time.perf_counter() - t0
    # ~2.6 MB at 2 MB/s with a 1 s burst allowance: the post-burst
    # deficit (~0.3 s) must have been slept off
    expected_min = (total - pacer.burst) / pacer.rate
    assert expected_min > 0
    assert elapsed >= expected_min * 0.9
    assert pacer.paced_sleep_s > 0
    snap = stats.snapshot()["counters"]
    assert snap.get("repair_paced_sleep_ms", 0) > 0


def test_pacer_unpaced_is_free():
    pacer = RepairPacer()  # both knobs 0
    t0 = time.perf_counter()
    for _ in range(1000):
        pacer.consume(1 << 20)
    assert time.perf_counter() - t0 < 0.5
    assert pacer.paced_sleep_s == 0


def test_pacer_inflight_bound():
    pacer = RepairPacer(max_inflight=2)
    active = {"now": 0, "max": 0}
    lock = threading.Lock()

    def transfer():
        with pacer.slot():
            with lock:
                active["now"] += 1
                active["max"] = max(active["max"], active["now"])
            time.sleep(0.05)
            with lock:
                active["now"] -= 1

    threads = [threading.Thread(target=transfer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert active["max"] <= 2


# ------------------------------------------------------------- merge rules


def test_merge_rules_preserved_mutex_and_bsi(tmp_path):
    """The conflict-aware repair semantics ride the fast path unchanged:
    mutex columns keep the LOCAL row; BSI columns are all-or-nothing."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        base = uri(servers[0])
        req("POST", f"{base}/index/i",
            {"options": {"trackExistence": False}})
        req("POST", f"{base}/index/i/field/m", {"options": {"type": "mutex"}})
        req("POST", f"{base}/index/i/field/v",
            {"options": {"type": "int", "min": 0, "max": 1000}})
        # replicated baseline: col 10 -> row 1, col 20 BSI 7 (both nodes)
        req("POST", f"{base}/index/i/query", b"Set(10, m=1)")
        req("POST", f"{base}/index/i/query", b"Set(20, v=7)")
        # node0-only divergence: col 10 moved to row 2 (mutex clears row
        # 1 locally); col 20 -> 999; col 30 fresh on node0 only
        f0m = servers[0].holder.index("i").field("m")
        f0m.set_bit(2, 10)
        f0v = servers[0].holder.index("i").field("v")
        f0v.set_value(20, 999)
        f0m.set_bit(0, 30)
        repaired = servers[1].api.cluster.sync_holder()
        assert repaired["bits"] >= 1
        f1m = servers[1].holder.index("i").field("m")
        f1v = servers[1].holder.index("i").field("v")
        frag1m = f1m.view("standard").fragment(0)
        # mutex: local row 1 wins over the peer's row 2; fresh col adopts
        assert frag1m.row_columns(1).tolist() == [10]
        assert 10 not in frag1m.row_columns(2).tolist()
        assert frag1m.row_columns(0).tolist() == [30]
        # BSI: locally existing value keeps ALL its planes
        assert f1v.value(20) == (7, True)
    finally:
        for s in servers:
            s.close()


# ------------------------------------------------------------------- knobs


def test_config_knobs_roundtrip_and_wiring(tmp_path):
    from pilosa_tpu.server import Server, ServerConfig

    cfg = ServerConfig.from_dict({
        "sync-workers": 3,
        "repair-max-bytes-per-sec": 12345,
        "repair-max-inflight": 2,
        "repair-compression": False,
    })
    assert cfg.sync_workers == 3
    assert cfg.repair_max_bytes_per_sec == 12345
    assert cfg.repair_max_inflight == 2
    assert cfg.repair_compression is False
    d = cfg.to_dict()
    assert d["sync-workers"] == 3
    assert d["repair-max-bytes-per-sec"] == 12345
    assert d["repair-max-inflight"] == 2
    assert d["repair-compression"] is False

    server = Server(ServerConfig(
        data_dir=str(tmp_path / "k"), port=0, name="k",
        anti_entropy_interval=0, heartbeat_interval=0, use_mesh=False,
        sync_workers=3, repair_max_bytes_per_sec=12345,
        repair_max_inflight=2, repair_compression=False,
    )).open()
    try:
        cluster = server.api.cluster
        assert cluster.sync_workers == 3
        assert cluster.client.pacer.rate == 12345
        assert cluster.client.pacer.max_inflight == 2
        assert cluster.client.compress_repair is False
    finally:
        server.close()


def test_sync_metrics_exported(tmp_path):
    """sync_manifest_* / sync_delta_blocks_* counters and the pass timer
    land on /metrics and /debug/vars after a repair."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        _seed_schema(servers[0])
        _diverge(servers[0], shards=(0,), seed=2)
        servers[1].api.cluster.sync_holder()
        metrics = req("GET", f"{uri(servers[1])}/metrics", raw=True).decode()
        assert "sync_manifest_fetches_total" in metrics
        assert "sync_delta_blocks_requests_total" in metrics
        assert "sync_delta_blocks_bytes_total" in metrics
        assert "sync_pass_seconds_count" in metrics
        dvars = req("GET", f"{uri(servers[1])}/debug/vars")
        assert dvars["counters"].get("sync_manifest_fetches", 0) >= 1
        assert "sync_pass" in dvars["distributions"]
        served = req("GET", f"{uri(servers[0])}/metrics",
                     raw=True).decode()
        assert "sync_manifest_served_total" in served
        assert "sync_delta_blocks_served_total" in served
    finally:
        for s in servers:
            s.close()


# ----------------------------------------------------------- mixed version


def _force_old_wire(servers, victim):
    """Make ``victim`` look like an old-wire, JSON-only node to every
    peer (and make its own client JSON-only): manifests/deltas 404-class
    fallback + protobuf 406 fallback, in both directions."""
    vuri = uri(victim)
    for s in servers:
        if s is victim:
            for other in servers:
                if other is not victim:
                    victim.api.cluster.client._json_only_peers.add(
                        uri(other))
                    victim.api.cluster.client._no_manifest_peers.add(
                        uri(other))
        else:
            s.api.cluster.client._json_only_peers.add(vuri)
            s.api.cluster.client._no_manifest_peers.add(vuri)


def test_mixed_version_cluster_randomized(tmp_path):
    """VERDICT r5 Next #5: a 3-node cluster with one node forced
    JSON-only AND old-wire (no manifest/delta routes) under the
    randomized property workload — manifest/delta negotiation and the r4
    proto renumbering cannot corrupt a mixed deployment. Every node must
    answer the full oracle after writes routed through ALL nodes and
    repair passes run from every node."""
    from test_property import (
        INT_MAX,
        INT_MIN,
        MUTEX_ROWS,
        ROWS,
        Oracle,
        random_workload,
    )

    rng = np.random.default_rng(42)
    servers = make_cluster(tmp_path, 3, replica_n=2, prefix="mixed")
    try:
        victim = servers[1]
        _force_old_wire(servers, victim)
        base = uri(servers[0])
        req("POST", f"{base}/index/i", {"options": {"trackExistence": True}})
        req("POST", f"{base}/index/i/field/f", {})
        req("POST", f"{base}/index/i/field/v",
            {"options": {"type": "int", "min": INT_MIN, "max": INT_MAX}})
        req("POST", f"{base}/index/i/field/m", {"options": {"type": "mutex"}})
        req("POST", f"{base}/index/i/field/b", {"options": {"type": "bool"}})
        req("POST", f"{base}/index/i/field/t",
            {"options": {"type": "time", "timeQuantum": "YMDH"}})

        class HttpEx:
            def execute(self, index, pql):
                s = servers[int(rng.integers(0, len(servers)))]
                return req(
                    "POST", f"{uri(s)}/index/{index}/query", pql.encode()
                )["results"]

        oracle = Oracle()
        random_workload(rng, HttpEx(), "i", oracle, n_ops=80)
        # repair from every node (victim uses the per-fragment path, the
        # others use manifests against each other and fall back for it)
        for s in servers:
            s.api.cluster.sync_holder()
        for s in servers:
            url = f"{uri(s)}/index/i/query"
            for row in ROWS:
                out = req("POST", url, f"Count(Row(f={row}))".encode())
                assert out["results"] == [len(oracle.sets[row])], (
                    s.config.name, row)
            out = req("POST", url, b"Row(f=1)")
            assert out["results"][0]["columns"] == sorted(oracle.sets[1])
            for row in MUTEX_ROWS:
                out = req("POST", url, f"Count(Row(m={row}))".encode())
                assert out["results"] == [len(oracle.mutex_row(row))]
            if oracle.values:
                out = req("POST", url, b'Sum(field="v")')
                assert out["results"][0] == {
                    "value": sum(oracle.values.values()),
                    "count": len(oracle.values),
                }, s.config.name
        # the old-wire fallback actually engaged: peers marked the victim
        for s in servers:
            if s is not victim:
                assert uri(victim) in (
                    s.api.cluster.client._no_manifest_peers)
    finally:
        for s in servers:
            s.close()


# -------------------------------------------------------------------- soak


def _rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


@pytest.mark.slow
def test_maintenance_soak_flat_rss_and_residency(tmp_path):
    """≥30-min (env-tunable) mixed read+write+churn+repair soak
    (VERDICT r5 Next #4): a replicated cluster serves queries and writes
    while a third node joins and leaves repeatedly and anti-entropy
    passes run throughout. Oracles: zero errors, exact counts at every
    checkpoint, flat RSS (the median of the last quarter within 25% + a
    32 MiB allowance of the first quarter's), and flat device-residency
    bytes."""
    from cluster_helpers import join_node
    from pilosa_tpu.storage.residency import global_row_cache

    duration = float(os.environ.get("PILOSA_SOAK_SECONDS", "1800"))
    servers = make_cluster(tmp_path, 2, replica_n=2, prefix="soak")
    third = None
    errors: list = []
    rss_samples: list[int] = []
    res_samples: list[int] = []
    try:
        base = uri(servers[0])
        req("POST", f"{base}/index/i", {"options": {"trackExistence": False}})
        req("POST", f"{base}/index/i/field/f", {})
        rng = np.random.default_rng(99)
        written: set[int] = set()
        deadline = time.monotonic() + duration
        round_no = 0
        while time.monotonic() < deadline:
            round_no += 1
            live = servers + ([third] if third is not None else [])
            try:
                # writes through a random live node
                cols = sorted(
                    int(c) for c in rng.integers(0, 4 * SHARD_WIDTH, 40)
                )
                target = live[int(rng.integers(0, len(live)))]
                req("POST", f"{uri(target)}/index/i/field/f/import",
                    {"rows": [1] * len(cols), "columns": cols})
                written.update(cols)
                # reads from every node must agree with the model
                for s in live:
                    out = req("POST", f"{uri(s)}/index/i/query",
                              b"Count(Row(f=1))")
                    if out["results"] != [len(written)]:
                        errors.append(
                            f"round {round_no}: {s.config.name} counted "
                            f"{out['results']} want {len(written)}"
                        )
                # divergence + repair: extra ROW-0 bits on node0 only
                # (row 1 stays the exact import-driven model), on a
                # shard node0 OWNS — anti-entropy syncs among a shard's
                # replicas, so divergence parked on a non-owner is
                # invisible to repair by design. Every live node runs a
                # pass; all must then AGREE on the divergent row.
                owned = [s for s in range(4)
                         if servers[0].api.cluster.owns_shard("i", s)]
                _diverge(
                    servers[0],
                    shards=(owned[int(rng.integers(0, len(owned)))],),
                    rows=1, bits=30, seed=round_no,
                )
                for s in live:
                    s.api.cluster.sync_holder()
                row0 = {
                    s.config.name: req(
                        "POST", f"{uri(s)}/index/i/query",
                        b"Count(Row(f=0))",
                    )["results"]
                    for s in live
                }
                if len(set(map(str, row0.values()))) != 1:
                    errors.append(
                        f"round {round_no}: post-repair divergence "
                        f"{row0}"
                    )
                # membership churn every few rounds
                if round_no % 5 == 0:
                    if third is None:
                        third = join_node(
                            tmp_path, servers[0], replica_n=2,
                            name="soak2", prefix=f"soak2-{round_no}",
                        )
                        if not third.api.cluster.wait_until_normal(60):
                            errors.append(f"round {round_no}: join stuck")
                    else:
                        third.api.cluster.leave()
                        third.close()
                        third = None
                        if not servers[0].api.cluster.wait_until_normal(60):
                            errors.append(f"round {round_no}: leave stuck")
            except Exception as e:  # noqa: BLE001 — soak oracle
                errors.append(f"round {round_no}: {e!r}")
                break
            rss_samples.append(_rss_kb())
            res_samples.append(
                int(global_row_cache().metrics().get(
                    "residency_bytes_used", 0))
            )
        assert not errors, errors[:5]
        assert round_no >= 4, "soak too short to judge slopes"
        q = max(1, len(rss_samples) // 4)
        first_rss = float(np.median(rss_samples[:q]))
        last_rss = float(np.median(rss_samples[-q:]))
        assert last_rss <= first_rss * 1.25 + 32 * 1024, (
            f"RSS slope: {first_rss} kB -> {last_rss} kB"
        )
        first_res = float(np.median(res_samples[:q]) or 0)
        last_res = float(np.median(res_samples[-q:]) or 0)
        assert last_res <= max(first_res * 1.5, first_res + (64 << 20)), (
            f"residency slope: {first_res} -> {last_res} bytes"
        )
    finally:
        if third is not None:
            third.close()
        for s in servers:
            s.close()
