"""HTTP integration tests: real listeners on ephemeral localhost ports
(reference http/handler_test.go httptest style — SURVEY.md §4)."""

import json
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.server.api import API
from pilosa_tpu.server.http import serve_in_thread
from pilosa_tpu.storage import Holder


@pytest.fixture
def node_api(tmp_path):
    holder = Holder(str(tmp_path / "data")).open()
    api = API(holder)
    server, port, _ = serve_in_thread(api)
    yield f"http://localhost:{port}", api
    server.shutdown()
    server.server_close()
    holder.close()


@pytest.fixture
def node(node_api):
    return node_api[0]


def req(method, url, body=None, content_type="application/json", raw=False):
    data = body if isinstance(body, (bytes, type(None))) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        r.add_header("Content-Type", content_type)
    with urllib.request.urlopen(r) as resp:
        payload = resp.read()
        return payload if raw else json.loads(payload or b"{}")


def test_full_lifecycle(node):
    # create index + fields
    req("POST", f"{node}/index/repos", {})
    req("POST", f"{node}/index/repos/field/stargazer", {})
    req("POST", f"{node}/index/repos/field/fare",
        {"options": {"type": "int", "min": 0, "max": 1000}})

    # schema surfaces both
    schema = req("GET", f"{node}/schema")
    names = {f["name"] for f in schema["indexes"][0]["fields"]}
    assert names == {"stargazer", "fare"}

    # writes via PQL query endpoint
    out = req("POST", f"{node}/index/repos/query",
              b"Set(10, stargazer=1) Set(20, stargazer=1)")
    assert out["results"] == [True, True]

    # read back
    out = req("POST", f"{node}/index/repos/query", b"Row(stargazer=1)")
    assert out["results"][0]["columns"] == [10, 20]

    # count fused
    out = req("POST", f"{node}/index/repos/query", b"Count(Row(stargazer=1))")
    assert out["results"] == [2]

    # BSI via import-value + Range/Sum
    req("POST", f"{node}/index/repos/field/fare/import-value",
        {"columns": [10, 20, 30], "values": [5, 10, 400]})
    out = req("POST", f"{node}/index/repos/query", b"Count(Range(fare > 6))")
    assert out["results"] == [2]
    out = req("POST", f"{node}/index/repos/query", b'Sum(field="fare")')
    assert out["results"][0] == {"value": 415, "count": 3}


def test_import_endpoint_and_export(node):
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    out = req("POST", f"{node}/index/i/field/f/import",
              {"rows": [1, 1, 2], "columns": [5, 9, 5]})
    assert out["changed"] == 3
    csv = req("GET", f"{node}/export?index=i&field=f", raw=True).decode()
    assert csv.splitlines() == ["1,5", "1,9", "2,5"]


def test_import_roaring_endpoint(node):
    from pilosa_tpu.roaring import RoaringBitmap, serialize

    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    # row 2, positions {1, 4} → fragment bits 2*2^20 + {1,4}
    bm = RoaringBitmap.from_ids([(2 << 20) + 1, (2 << 20) + 4])
    out = req("POST", f"{node}/index/i/field/f/import-roaring/0",
              serialize(bm), content_type="application/octet-stream")
    assert out["changed"] == 2
    out = req("POST", f"{node}/index/i/query", b"Row(f=2)")
    assert out["results"][0]["columns"] == [1, 4]


def test_topn_groupby_over_http(node):
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    rows, cols = [], []
    for row, n in [(1, 3), (2, 8), (3, 5)]:
        rows += [row] * n
        cols += list(range(n))
    req("POST", f"{node}/index/i/field/f/import", {"rows": rows, "columns": cols})
    out = req("POST", f"{node}/index/i/query", b"TopN(f, n=2)")
    assert out["results"][0] == [{"id": 2, "count": 8}, {"id": 3, "count": 5}]
    out = req("POST", f"{node}/index/i/query", b"GroupBy(Rows(f), limit=2)")
    assert out["results"][0] == [
        {"group": [{"field": "f", "rowID": 1}], "count": 3},
        {"group": [{"field": "f", "rowID": 2}], "count": 8},
    ]


def test_recalculate_caches_repairs_drift(node_api):
    """POST /recalculate-caches (reference parity): an authoritative
    recount rebuilds a drifted TopN row cache from container
    cardinalities and persists it; returns 204."""
    node, api = node_api
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    rows, cols = [], []
    for row, n in [(1, 3), (2, 8), (3, 5)]:
        rows += [row] * n
        cols += list(range(n))
    req("POST", f"{node}/index/i/field/f/import", {"rows": rows, "columns": cols})

    # simulate drift: clobber the cache with wrong counts (as a crash
    # between bitmap flush and cache save, or a hand-edited dir, would).
    # Phase-2 TopN recounts exactly, so at this scale queries hide the
    # drift — the endpoint's contract is that the CACHE returns to the
    # authoritative counts and persists them.
    frag = api.holder.indexes["i"].fields["f"].views["standard"].fragments[0]
    frag.row_cache.bulk_add(1, 999)
    frag.row_cache.bulk_add(2, 1)
    frag.row_cache.bulk_add(7, 42)  # phantom row: must vanish

    r = urllib.request.Request(f"{node}/recalculate-caches", data=b"{}",
                               method="POST")  # non-empty body: must drain
    with urllib.request.urlopen(r) as resp:
        assert resp.status == 204
        assert resp.headers.get("Content-Length") is None  # RFC 7230 204
    # 204 means QUEUED: the recount runs in a background worker so the
    # cluster message-delivery path can't stall on it (ADVICE r5) — join
    # the worker before asserting on the repaired cache
    t = api._recalc_thread
    if t is not None:
        t.join(timeout=30)
    cache = api.holder.indexes["i"].fields["f"].views["standard"] \
        .fragments[0].row_cache
    assert cache.get(1) == 3 and cache.get(2) == 8 and cache.get(3) == 5
    assert cache.get(7) is None
    # recount persisted: a reloaded cache sees the repaired counts
    fresh = type(cache)(cache.max_size)
    fresh.load(frag._cache_path())
    assert fresh.get(1) == 3 and fresh.get(7) is None
    out = req("POST", f"{node}/index/i/query", b"TopN(f, n=2)")
    assert out["results"][0] == [{"id": 2, "count": 8}, {"id": 3, "count": 5}]


def test_status_info_version_metrics(node):
    st = req("GET", f"{node}/status")
    assert st["state"] == "NORMAL" and st["nodes"]
    info = req("GET", f"{node}/info")
    assert info["shardWidth"] == 1 << 20
    v = req("GET", f"{node}/version")
    assert v["version"]
    # metrics endpoint serves prometheus text incl. residency gauges:
    # counters carry _total, values are exact ints (no %g truncation)
    text = req("GET", f"{node}/metrics", raw=True).decode()
    assert "pilosa_tpu_residency_bytes_used" in text
    assert "pilosa_tpu_residency_hits_total" in text
    # run one pipelined read, then the wave-coalescing counters must
    # be exported for operators (and exist as 0 even before it)
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query", b"Set(1, f=1)")
    req("POST", f"{node}/index/i/query", b"Count(Row(f=1))")
    text = req("GET", f"{node}/metrics", raw=True).decode()
    assert "pilosa_tpu_serving_waves_total" in text
    # host-path kernel counters present from scrape one (PR 18) — and
    # the query above decoded at least one row through the kernels
    assert "pilosa_tpu_hostpath_kernel_calls_total" in text
    kline = [l for l in text.splitlines()
             if l.startswith("pilosa_tpu_hostpath_kernel_calls_total")]
    assert int(kline[0].split()[1]) > 0
    (budget_line,) = [l for l in text.splitlines()
                      if l.startswith("pilosa_tpu_residency_budget_bytes")]
    dv = req("GET", f"{node}/debug/vars")
    # exact int emission (no %g scientific-notation truncation)
    assert budget_line.split()[1] == str(dv["residency"]["residency_budget_bytes"])


def test_error_statuses(node):
    # query on missing index → 400 with error body
    with pytest.raises(urllib.error.HTTPError) as e:
        req("POST", f"{node}/index/nope/query", b"Row(f=1)")
    assert e.value.code == 400
    # delete missing index → 404
    with pytest.raises(urllib.error.HTTPError) as e:
        req("DELETE", f"{node}/index/nope")
    assert e.value.code == 404
    # duplicate create → 409
    req("POST", f"{node}/index/i", {})
    with pytest.raises(urllib.error.HTTPError) as e:
        req("POST", f"{node}/index/i", {})
    assert e.value.code == 409
    # bad PQL → 400 with parse error message
    with pytest.raises(urllib.error.HTTPError) as e:
        req("POST", f"{node}/index/i/query", b"Bogus(")
    assert e.value.code == 400
    assert "error" in json.loads(e.value.read())
    # unknown route → 404
    with pytest.raises(urllib.error.HTTPError) as e:
        req("GET", f"{node}/definitely/not/a/route")
    assert e.value.code == 404


def test_delete_field_and_index(node):
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query", b"Set(1, f=1)")
    req("DELETE", f"{node}/index/i/field/f")
    schema = req("GET", f"{node}/schema")
    assert schema["indexes"][0]["fields"] == []
    req("DELETE", f"{node}/index/i")
    assert req("GET", f"{node}/schema") == {"indexes": []}


def test_internal_fragment_blocks_and_data(node):
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query", b"Set(1, f=1) Set(5, f=101)")
    out = req("GET", f"{node}/internal/fragment/blocks?index=i&field=f&view=standard&shard=0")
    assert {b["block"] for b in out["blocks"]} == {0, 1}
    raw = req("GET", f"{node}/internal/fragment/data?index=i&field=f&view=standard&shard=0", raw=True)
    from pilosa_tpu.roaring.format import load

    bm, _ = load(raw)
    assert bm.count() == 2


def test_shards_max(node):
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query", b"Set(1, f=1)")
    out = req("GET", f"{node}/internal/shards/max")
    assert out["standard"]["i"] == 0


def test_long_query_log(node_api):
    node, api = node_api
    api.long_query_time = 0.0000001  # everything is "long"
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query", b"Set(1, f=1)")
    out = req("GET", f"{node}/debug/long-queries")
    assert out["threshold"] == api.long_query_time
    assert any(q["pql"] == "Set(1, f=1)" for q in out["queries"])
    # threshold off -> nothing more recorded
    api.long_query_time = 0.0
    api.long_queries.clear()
    req("POST", f"{node}/index/i/query", b"Count(Row(f=1))")
    assert req("GET", f"{node}/debug/long-queries")["queries"] == []


@pytest.mark.skipif(__import__("shutil").which("openssl") is None,
                    reason="openssl binary not available")
def test_tls_server(tmp_path):
    import subprocess

    from pilosa_tpu.parallel.client import InternalClient
    from pilosa_tpu.server.server import Server, ServerConfig

    cert = tmp_path / "node.crt"
    key = tmp_path / "node.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )
    cfg = ServerConfig(
        data_dir=str(tmp_path / "data"), port=0, use_mesh=False,
        anti_entropy_interval=0, heartbeat_interval=0,
        tls_certificate=str(cert), tls_key=str(key), tls_skip_verify=True,
    )
    server = Server(cfg).open()
    try:
        uri = f"https://localhost:{server.port}"
        assert server.api.cluster.local.uri.startswith("https://")
        # the server's own internal client got skip-verify from its config
        assert server.api.cluster.client._ssl_context is not None
        client = InternalClient(insecure_tls=True)
        client._call("POST", f"{uri}/index/i", json.dumps({}).encode())
        client._call("POST", f"{uri}/index/i/field/f", json.dumps({}).encode())
        out = client.query_node(uri, "i", "Set(3, f=1) Count(Row(f=1))",
                                shards=[0], remote=False)
        assert out["results"] == [True, 1]
        # plain http against the TLS socket must fail (URLError or a
        # straight connection reset depending on handshake timing)
        with pytest.raises(OSError):
            urllib.request.urlopen(f"http://localhost:{server.port}/schema", timeout=5)
    finally:
        server.close()


def test_parse_duration():
    from pilosa_tpu.server.server import _parse_duration

    assert _parse_duration(1.5) == 1.5
    assert _parse_duration("30s") == 30.0
    assert _parse_duration("1m30s") == 90.0
    assert _parse_duration("500ms") == 0.5
    assert _parse_duration("2h") == 7200.0
    assert _parse_duration("") == 0.0
    assert _parse_duration("0.25") == 0.25


def test_parse_duration_rejects_malformed():
    from pilosa_tpu.server.server import _parse_duration

    for bad in ("1m30", "abc", "10x", "s30"):
        with pytest.raises(ValueError):
            _parse_duration(bad)


def test_parse_duration_rejects_double_dot():
    from pilosa_tpu.server.server import _parse_duration

    for bad in ("1.2.3s", "..5s", "1..s"):
        with pytest.raises(ValueError):
            _parse_duration(bad)
    assert _parse_duration(".5s") == 0.5


def test_config_to_dict_round_trips_new_keys():
    from pilosa_tpu.server.server import ServerConfig

    cfg = ServerConfig(long_query_time=1.5, tls_certificate="/c", tls_key="/k",
                       tls_skip_verify=True)
    d = cfg.to_dict()
    assert d["long-query-time"] == 1.5
    assert d["tls-certificate"] == "/c" and d["tls-key"] == "/k"
    assert d["tls-skip-verify"] is True
    back = ServerConfig.from_dict(d)
    assert back.long_query_time == 1.5 and back.tls_enabled


def test_insecure_tls_is_per_client():
    # One skip-verify client must not disable verification for others in
    # the same process (ADVICE r1: scope the SSL context to the instance).
    from pilosa_tpu.parallel.client import InternalClient

    insecure = InternalClient(insecure_tls=True)
    secure = InternalClient()
    assert insecure._ssl_context is not None
    assert insecure._ssl_context.verify_mode == __import__("ssl").CERT_NONE
    assert secure._ssl_context is None


def test_max_writes_per_request(node_api):
    node, api = node_api
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    api.max_writes_per_request = 3
    ok = " ".join(f"Set({c}, f=1)" for c in range(3))
    assert req("POST", f"{node}/index/i/query", ok.encode())["results"] == [True] * 3
    too_many = " ".join(f"Set({c}, f=1)" for c in range(10, 14))
    with pytest.raises(urllib.error.HTTPError) as e:
        req("POST", f"{node}/index/i/query", too_many.encode())
    assert e.value.code == 400
    assert "max-writes-per-request" in json.loads(e.value.read())["error"]
    # reads are unaffected
    assert req("POST", f"{node}/index/i/query", b"Count(Row(f=1))")["results"] == [3]


def test_import_roaring_edge_respects_max_writes(node_api):
    """max-writes-per-request covers the roaring route's EDGE bodies too
    (413, like /import) — the cheapest encoding must not bypass the
    admission limit; routed internal slices (?remote=true) are exempt."""
    from pilosa_tpu.roaring import RoaringBitmap, serialize

    node, api = node_api
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    api.max_writes_per_request = 3
    body = serialize(RoaringBitmap.from_ids([1, 2, 3, 4, 5]))
    with pytest.raises(urllib.error.HTTPError) as e:
        req("POST", f"{node}/index/i/field/f/import-roaring/0", body,
            content_type="application/octet-stream")
    assert e.value.code == 413
    out = req("POST",
              f"{node}/index/i/field/f/import-roaring/0?remote=true",
              body, content_type="application/octet-stream")
    assert out["changed"] == 5


def test_bind_failure_raises_oserror_not_attributeerror():
    """TCPServer.__init__ calls server_close on a bind failure; the
    connection registry must already exist so the REAL error (port in
    use) surfaces."""
    import socket

    from pilosa_tpu.server.http import make_http_server

    srv = socket.create_server(("localhost", 0))
    busy_port = srv.getsockname()[1]
    try:
        with pytest.raises(OSError):
            make_http_server(None, "localhost", busy_port)
    finally:
        srv.close()


def test_import_roaring_malformed_upstream_blob_is_400(node):
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    # pilosa cookie (12348) but truncated body: clean 400, not a 500
    with pytest.raises(urllib.error.HTTPError) as e:
        req("POST", f"{node}/index/i/field/f/import-roaring/0",
            b"\x3c\x30\x00\x00\x01", content_type="application/octet-stream")
    assert e.value.code == 400


def test_request_level_query_options(node):
    """URL params columnAttrs / excludeColumns / excludeRowAttrs apply to
    row results of the whole request (reference handler query args;
    SURVEY-MED spelling — names mirror the PQL Options() args)."""
    req("POST", f"{node}/index/i", {})
    req("POST", f"{node}/index/i/field/f", {})
    req("POST", f"{node}/index/i/query",
        b'Set(1, f=1) Set(2, f=1) SetColumnAttrs(1, city="nyc") '
        b'SetRowAttrs(f, 1, team="blue")')
    base = req("POST", f"{node}/index/i/query", b"Row(f=1)")["results"][0]
    assert base["columns"] == [1, 2] and base["attrs"] == {"team": "blue"}

    out = req("POST", f"{node}/index/i/query?columnAttrs=true",
              b"Row(f=1)")["results"][0]
    assert out["columnAttrs"] == [{"id": 1, "attrs": {"city": "nyc"}}]

    out = req("POST", f"{node}/index/i/query?excludeRowAttrs=true",
              b"Row(f=1)")["results"][0]
    assert out["attrs"] == {} and out["columns"] == [1, 2]

    out = req("POST",
              f"{node}/index/i/query?excludeColumns=true&columnAttrs=true",
              b"Row(f=1)")["results"][0]
    assert out["columns"] == [] and out["attrs"] == {"team": "blue"}
    assert out["columnAttrs"] == [{"id": 1, "attrs": {"city": "nyc"}}]


def test_fragment_nodes_route(node):
    """GET /internal/fragment/nodes reports shard ownership (reference
    clients route imports/queries with it)."""
    req("POST", f"{node}/index/i", {})
    out = req("GET", f"{node}/internal/fragment/nodes?index=i&shard=3")
    assert isinstance(out, list) and out and "uri" in out[0]


def test_import_with_timestamps_lands_in_time_views(node):
    """Timestamped bulk import writes the standard view AND each quantum
    view (batched per view, not per bit); Row(from=, to=) sees them."""
    req("POST", f"{node}/index/t", {})
    req("POST", f"{node}/index/t/field/ev",
        {"options": {"type": "time", "timeQuantum": "YMD"}})
    out = req("POST", f"{node}/index/t/field/ev/import", {
        "rows": [1, 1, 1, 2],
        "columns": [10, 11, 12, 10],
        "timestamps": ["2019-01-15T00:00", "2019-03-02T00:00",
                       None, "2019-01-15T00:00"],
    })
    assert out["changed"] == 4
    out = req("POST", f"{node}/index/t/query", b"Row(ev=1)")
    assert out["results"][0]["columns"] == [10, 11, 12]
    out = req("POST", f"{node}/index/t/query",
              b"Row(ev=1, from='2019-01-01T00:00', to='2019-02-01T00:00')")
    assert out["results"][0]["columns"] == [10]
    out = req("POST", f"{node}/index/t/query",
              b"Row(ev=1, from='2019-01-01T00:00', to='2019-12-31T00:00')")
    assert out["results"][0]["columns"] == [10, 11]
    # the un-timestamped bit exists only in standard
    out = req("POST", f"{node}/index/t/query",
              b"Row(ev=2, from='2019-01-01T00:00', to='2019-02-01T00:00')")
    assert out["results"][0]["columns"] == [10]
