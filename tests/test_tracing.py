"""Distributed tracing, in-flight inspector, and metrics-plane suite
(ISSUE 7 / docs/OBSERVABILITY.md).

Oracles:
- context propagation: every span of a request is reachable from its
  request root (pool fan-outs, the serving pipeline's wave handoff, and
  the micro-batcher included) — none orphaned;
- cross-node stitching: a 3-node cluster query yields ONE tree on the
  coordinator containing remote child spans from both peers with intact
  parent/trace ids;
- sampling statistics and the zero-overhead off path (no spans retained,
  no context mutation, shared no-op handle);
- the slow-query ring captures full span trees;
- /debug/queries shows then clears an in-flight query;
- /metrics is stock-Prometheus parseable with HELP/TYPE per family and
  cumulative histogram series beside the windowed summaries.
"""

import json
import re
import threading
import time
import urllib.request

import pytest

from cluster_helpers import make_cluster, req, seed, uri
from pilosa_tpu.utils.tracing import (
    TRACE_HEADER,
    Tracer,
    current_span,
    global_query_tracker,
    global_tracer,
    parse_trace_header,
)


@pytest.fixture(autouse=True)
def _reset_tracing():
    """Every test starts with sampling off and empty rings, and leaves
    the process-global tracer/tracker the way tier-1 expects them."""
    tracer = global_tracer()
    tracker = global_query_tracker()
    tracer.sample_rate = 0.0
    tracer.clear()
    tracker.enabled = True
    yield
    tracer.sample_rate = 0.0
    tracer.clear()
    tracker.enabled = True


def _walk(tree, out=None):
    out = out if out is not None else []
    out.append(tree)
    for child in tree.get("children", []):
        _walk(child, out)
    return out


def _assert_tree_consistent(tree):
    """Every span shares the root's traceId and each child's parentId is
    its parent's spanId — the 'reachable from root, none orphaned'
    oracle."""
    trace_id = tree["traceId"]

    def rec(node):
        assert node["traceId"] == trace_id, node
        for child in node.get("children", []):
            assert child["parentId"] == node["spanId"], (node, child)
            rec(child)

    rec(tree)


# --------------------------------------------------------------- unit level


class TestTracerCore:
    def test_span_tree_and_ids(self):
        t = Tracer(sample_rate=1.0)
        with t.root_span("root", a=1) as root:
            with t.span("child") as child:
                assert current_span() is child
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
            assert current_span() is root
        assert current_span() is None
        assert len(t.finished) == 1
        _assert_tree_consistent(t.recent()[0])

    def test_off_is_noop_no_allocation_no_context(self):
        t = Tracer(sample_rate=0.0)
        before = current_span()
        h1 = t.span("x")
        h2 = t.request_root("y")
        # zero-allocation: the shared no-op handle, same object every time
        assert h1 is h2 is t.span("z")
        with h1 as s:
            assert s is None
            assert current_span() is before is None
        assert len(t.finished) == 0
        assert t.spans_started == 0

    def test_unsampled_request_suppresses_inner_roots(self):
        t = Tracer(sample_rate=0.5)
        # force the negative decision deterministically
        import random

        random.seed(0)
        for _ in range(200):
            with t.request_root("http.query") as root:
                if root is None:
                    # inner span sites must NOT root their own trace
                    with t.span("executor.Execute") as inner:
                        assert inner is None
        # every finished tree is rooted at the request root
        assert all(s.name == "http.query" for s in t.finished)

    def test_sampling_rate_statistics(self):
        t = Tracer(sample_rate=0.25)
        n = 2000
        hits = 0
        for _ in range(n):
            with t.request_root("r") as root:
                if root is not None:
                    hits += 1
        # mean 500, sd ~19.4 — 5 sigma bounds
        assert 400 < hits < 600, hits
        assert t.sampled_traces == hits

    def test_header_roundtrip_and_remote_root(self):
        t = Tracer(sample_rate=1.0)
        with t.root_span("root") as root:
            header = root.header_value()
        assert parse_trace_header(header) == (root.trace_id, root.span_id)
        assert parse_trace_header(None) is None
        assert parse_trace_header("garbage") is None
        with t.remote_root(header, "rpc.query", node="n1") as remote:
            assert remote.trace_id == root.trace_id
            assert remote.parent_id == root.span_id
        # malformed header: suppressed, not sampled locally
        with t.remote_root("bad", "rpc.query") as none_span:
            assert none_span is None
            with t.span("inner") as inner:
                assert inner is None

    def test_context_propagates_through_pool(self):
        from pilosa_tpu.utils.pool import concurrent_map, spawn

        t = Tracer(sample_rate=1.0)
        with t.root_span("root") as root:
            names = concurrent_map(
                lambda i: (current_span() or root).trace_id, range(8)
            )
            assert all(tid == root.trace_id for tid in names)

            def thunk():
                with t.span("spawned") as s:
                    return s.trace_id

            assert spawn(thunk)() == root.trace_id
        tree = t.recent()[0]
        assert "spawned" in [c["name"] for c in tree["children"]]
        _assert_tree_consistent(tree)


# ------------------------------------------------------------- single node


@pytest.fixture()
def server(tmp_path):
    from pilosa_tpu.server import Server, ServerConfig

    s = Server(ServerConfig(
        data_dir=str(tmp_path / "node"), port=0, name="t",
        anti_entropy_interval=0, heartbeat_interval=0,
    )).open()
    yield s
    s.close()


def _seed_single(s):
    base = uri(s)
    req("POST", f"{base}/index/i", {})
    req("POST", f"{base}/index/i/field/f", {})
    req("POST", f"{base}/index/i/field/f/import",
        {"rows": [1, 1, 2], "columns": [1, 2, 2]})


class TestSingleNode:
    def test_pipeline_span_tree_reachable_from_http_root(self, server):
        _seed_single(server)
        global_tracer().sample_rate = 1.0
        for _ in range(3):
            req("POST", f"{uri(server)}/index/i/query",
                b"Count(Row(f=1))")
        traces = req("GET", f"{uri(server)}/debug/traces")
        assert traces["enabled"] and traces["sampleRate"] == 1.0
        query_trees = [t for t in traces["traces"]
                       if t["name"] == "http.query"]
        assert len(query_trees) == 3
        for tree in query_trees:
            _assert_tree_consistent(tree)
            names = [n["name"] for n in _walk(tree)]
            # the per-stage attribution the acceptance criterion names
            assert "qos.admit" in names
            assert "pipeline.wave" in names
            assert "executor.Execute" in names
            assert "executeCount" in names
            assert "device.dispatch" in names

    def test_no_spans_when_off_and_inflight_always_on(self, server):
        _seed_single(server)
        req("POST", f"{uri(server)}/index/i/query", b"Count(Row(f=1))")
        traces = req("GET", f"{uri(server)}/debug/traces")
        assert traces["traces"] == []
        assert traces["sampleRate"] == 0.0
        # the inspector tracked it even with tracing off
        q = req("GET", f"{uri(server)}/debug/queries")
        assert q["trackedTotal"] >= 1 and q["queries"] == []

    def test_write_gets_wal_barrier_span(self, server):
        _seed_single(server)
        global_tracer().sample_rate = 1.0
        req("POST", f"{uri(server)}/index/i/query", b"Set(5, f=3)")
        trees = req("GET", f"{uri(server)}/debug/traces")["traces"]
        names = [n["name"] for t in trees for n in _walk(t)]
        assert "wal.barrier" in names

    def test_inflight_query_shows_stage_then_clears(self, server):
        _seed_single(server)
        gate = threading.Event()
        release = threading.Event()
        admission = server.api.qos.admission
        real_admit = admission.admit

        def slow_admit(tenant="default"):
            gate.set()
            release.wait(10)
            return real_admit(tenant)

        admission.admit = slow_admit
        try:
            worker = threading.Thread(
                target=lambda: req("POST", f"{uri(server)}/index/i/query",
                                   b"Count(Row(f=1))"),
                daemon=True,
            )
            worker.start()
            assert gate.wait(10)
            q = req("GET", f"{uri(server)}/debug/queries")
            assert len(q["queries"]) == 1
            entry = q["queries"][0]
            assert entry["pql"] == "Count(Row(f=1))"
            assert entry["index"] == "i"
            assert entry["stage"] == "admission"
            assert entry["ageSeconds"] >= 0
            release.set()
            worker.join(30)
            deadline = time.time() + 10
            while time.time() < deadline:
                if not req("GET",
                           f"{uri(server)}/debug/queries")["queries"]:
                    break
                time.sleep(0.05)
            assert not req("GET",
                           f"{uri(server)}/debug/queries")["queries"]
        finally:
            release.set()
            admission.admit = real_admit

    def test_slow_query_ring_captures_span_tree(self, server):
        _seed_single(server)
        global_tracer().sample_rate = 1.0
        server.api.long_query_time = 1e-9  # everything is "slow"
        req("POST", f"{uri(server)}/index/i/query", b"Count(Row(f=1))")
        out = req("GET", f"{uri(server)}/debug/queries/slow")
        assert out["threshold"] == pytest.approx(1e-9)
        assert out["total"] >= 1
        entry = out["queries"][-1]
        assert entry["pql"] == "Count(Row(f=1))"
        assert "trace" in entry and "traceId" in entry
        names = [n["name"] for n in _walk(entry["trace"])]
        assert "executor.Execute" in names
        _assert_tree_consistent(entry["trace"])
        # the legacy alias keeps answering
        legacy = req("GET", f"{uri(server)}/debug/long-queries")
        assert legacy["queries"]
        # counter exported on /metrics from this node's API counter
        metrics = req("GET", f"{uri(server)}/metrics", raw=True).decode()
        m = re.search(r"^pilosa_tpu_slow_queries_total (\d+)", metrics,
                      re.M)
        assert m and int(m.group(1)) >= 1

    def test_trace_device_capture(self, server):
        out = req("POST",
                  f"{uri(server)}/debug/trace-device?secs=0.2", b"")
        assert out["seconds"] >= 0.2
        import os

        assert os.path.isdir(out["logDir"])
        # the profiler wrote something under the log dir
        found = any(files for _, _, files in os.walk(out["logDir"]))
        assert found, f"empty trace dir {out['logDir']}"

    def test_trace_device_rejects_bad_secs(self, server):
        for bad in ("0", "-1", "61", "nan", "x"):
            with pytest.raises(urllib.error.HTTPError) as err:
                req("POST",
                    f"{uri(server)}/debug/trace-device?secs={bad}", b"")
            assert err.value.code == 400


# ------------------------------------------------------------ metrics plane


def _parse_prometheus(text):
    """Minimal exposition-format checker: returns (families: dict
    name->type, samples: list of (name, value)). Raises AssertionError
    on any malformed line."""
    families = {}
    samples = []
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? "
        r"([-+]?(?:[0-9.]+(?:[eE][-+]?[0-9]+)?|[Ii]nf|NaN))$"
    )
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            assert len(line.split(None, 3)) == 4, line
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert parts[3] in ("counter", "gauge", "summary",
                                "histogram"), line
            families[parts[2]] = parts[3]
            continue
        assert not line.startswith("#"), line
        m = sample_re.match(line)
        assert m, f"malformed sample line: {line!r}"
        samples.append((m.group(1), m.group(3)))
    return families, samples


def _family_of(name, families):
    """Map a sample name to its declared family (strip summary/histogram
    child suffixes)."""
    if name in families:
        return name
    for suffix in ("_bucket", "_sum", "_count", "_total"):
        if name.endswith(suffix) and name[: -len(suffix)] in families:
            return name[: -len(suffix)]
    return None


class TestMetricsPlane:
    def test_metrics_prometheus_compliant(self, server):
        _seed_single(server)
        global_tracer().sample_rate = 1.0
        req("POST", f"{uri(server)}/index/i/query", b"Count(Row(f=1))")
        text = req("GET", f"{uri(server)}/metrics", raw=True).decode()
        families, samples = _parse_prometheus(text)
        assert samples, "empty /metrics"
        # every series belongs to a declared family (HELP/TYPE present)
        orphans = [n for n, _ in samples
                   if _family_of(n, families) is None]
        assert not orphans, f"series without TYPE metadata: {orphans[:5]}"
        # no family declared twice
        type_lines = [l for l in text.splitlines()
                      if l.startswith("# TYPE ")]
        assert len(type_lines) == len(set(type_lines))
        # observability series present from scrape one
        for needle in ("pilosa_tpu_slow_queries_total",
                       "pilosa_tpu_tracing_sampled_traces_total",
                       "pilosa_tpu_inflight_queries"):
            assert needle in {n for n, _ in samples}, needle

    def test_timer_histogram_export(self):
        from pilosa_tpu.utils.stats import StatsClient

        s = StatsClient()
        for v in (0.0004, 0.003, 0.003, 0.2, 9.0, 99.0):
            s.timing("query", v)
        text = s.prometheus_text()
        families, samples = _parse_prometheus(text)
        assert families["pilosa_tpu_query_seconds"] == "summary"
        assert families["pilosa_tpu_query_hist_seconds"] == "histogram"
        by_name = {}
        for n, v in samples:
            by_name.setdefault(n, []).append(v)
        buckets = {}
        for line in text.splitlines():
            m = re.match(
                r'pilosa_tpu_query_hist_seconds_bucket\{le="([^"]+)"\} '
                r"(\d+)", line)
            if m:
                buckets[m.group(1)] = int(m.group(2))
        # cumulative: le=0.001 has 1, le=0.005 has 3, le=10 has 5,
        # +Inf has all 6 (99.0 lands only in +Inf)
        assert buckets["0.001"] == 1
        assert buckets["0.005"] == 3
        assert buckets["10"] == 5
        assert buckets["+Inf"] == 6
        assert by_name["pilosa_tpu_query_hist_seconds_count"] == ["6"]

    def test_debug_vars_observability_block(self, server):
        snap = req("GET", f"{uri(server)}/debug/vars")
        obs = snap["observability"]
        for key in ("slow_queries_total", "tracing_sample_rate",
                    "inflight_queries", "queries_tracked_total"):
            assert key in obs, key


# -------------------------------------------------------------- three nodes


class TestClusterStitching:
    def test_remote_span_tree_stitched_on_coordinator(self, tmp_path):
        servers = make_cluster(tmp_path, 3, trace_sample_rate=1.0)
        try:
            seed(servers[0], n_shards=9)
            out = req("POST", f"{uri(servers[0])}/index/i/query",
                      b"Count(Row(f=1))")
            assert out == {"results": [36]}
            trees = req("GET",
                        f"{uri(servers[0])}/debug/traces")["traces"]
            tree = next(t for t in reversed(trees)
                        if t["name"] == "http.query")
            _assert_tree_consistent(tree)
            spans = _walk(tree)
            remote_legs = [s for s in spans
                           if s["name"] == "remote.query"]
            leg_nodes = {s["tags"]["node"] for s in remote_legs}
            assert leg_nodes == {"n1", "n2"}, leg_nodes
            # each leg carries the PEER's returned subtree, parented to
            # the leg's span id, with per-stage times from the peer
            for leg in remote_legs:
                sub = [c for c in leg["children"]
                       if c["name"] == "rpc.query"]
                assert sub, leg
                assert sub[0]["parentId"] == leg["spanId"]
                assert sub[0]["traceId"] == tree["traceId"]
                peer_names = [n["name"] for n in _walk(sub[0])]
                assert "executor.Execute" in peer_names
            # coordinator stages present too
            names = [s["name"] for s in spans]
            for stage in ("qos.admit", "pipeline.wave",
                          "executor.Execute", "device.dispatch"):
                assert stage in names, stage
        finally:
            for s in servers:
                s.close()

    def test_batched_wave_keeps_per_item_traces(self, tmp_path):
        """Concurrent sampled queries ride the wave batcher's shared
        POST; every request must still get its own stitched tree."""
        servers = make_cluster(tmp_path, 2, trace_sample_rate=1.0)
        try:
            seed(servers[0], n_shards=6)
            n = 8
            results = [None] * n
            gate = threading.Event()

            def worker(k):
                gate.wait(10)
                # distinct PQL strings (leading spaces) defeat the
                # pipeline's identical-query dedupe — a deduped follower
                # legitimately has NO remote leg of its own, which is
                # exactly what this test must not conflate with a lost
                # trace context
                results[k] = req(
                    "POST", f"{uri(servers[0])}/index/i/query",
                    b" " * k + b"Count(Row(f=1))")

            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(n)]
            for t in threads:
                t.start()
            gate.set()
            for t in threads:
                t.join(60)
            assert all(r == {"results": [24]} for r in results), results
            trees = [t for t in
                     req("GET",
                         f"{uri(servers[0])}/debug/traces")["traces"]
                     if t["name"] == "http.query"]
            assert len(trees) == n
            stitched = 0
            for tree in trees:
                _assert_tree_consistent(tree)
                for s in _walk(tree):
                    if s["name"] == "rpc.query":
                        stitched += 1
            # every request that crossed the wire got its subtree back
            # (local-only routings are possible for some, but with 6
            # shards on 2 nodes every query has a remote leg)
            assert stitched >= n
            batcher = servers[0].api.executor.wave_batcher.metrics()
            assert (batcher["remote_batched_queries_total"]
                    + batcher["remote_batch_solo_total"]) >= n
        finally:
            for s in servers:
                s.close()

    def test_sync_pass_traces_and_remote_sync_spans(self, tmp_path):
        servers = make_cluster(tmp_path, 2, replica_n=2,
                               trace_sample_rate=1.0)
        try:
            seed(servers[0], n_shards=4)
            global_tracer().clear()
            servers[0].run_anti_entropy()
            trees = global_tracer().recent()
            sync_trees = [t for t in trees if t["name"] == "sync.pass"]
            assert sync_trees
            names = [n["name"] for t in sync_trees for n in _walk(t)]
            assert "sync.manifest" in names
        finally:
            for s in servers:
                s.close()


# -------------------------------------------------------------- config knob


class TestConfigKnobs:
    def test_sample_rate_roundtrip(self):
        from pilosa_tpu.server import ServerConfig

        cfg = ServerConfig.from_dict({"trace-sample-rate": "0.25",
                                      "trace-log-dir": "/tmp/tr"})
        assert cfg.trace_sample_rate == 0.25
        assert cfg.trace_log_dir == "/tmp/tr"
        d = cfg.to_dict()
        assert d["trace-sample-rate"] == 0.25
        assert d["trace-log-dir"] == "/tmp/tr"
        assert ServerConfig.from_dict(d).trace_sample_rate == 0.25

    def test_sample_rate_validation(self):
        from pilosa_tpu.server import ServerConfig

        with pytest.raises(ValueError):
            ServerConfig(trace_sample_rate=1.5)
        with pytest.raises(ValueError):
            ServerConfig(trace_sample_rate=-0.1)

    def test_legacy_tracing_bool_means_rate_one(self, tmp_path):
        from pilosa_tpu.server import Server, ServerConfig

        s = Server(ServerConfig(
            data_dir=str(tmp_path / "n"), port=0, tracing=True,
            anti_entropy_interval=0, heartbeat_interval=0,
        )).open()
        try:
            assert global_tracer().sample_rate == 1.0
        finally:
            s.close()

    def test_generate_config_documents_knobs(self, capsys):
        from pilosa_tpu.cli import main

        assert main(["generate-config"]) == 0
        out = capsys.readouterr().out
        assert "trace-sample-rate" in out
        assert "long-query-time" in out


class TestObsSmoke:
    def test_obs_smoke(self, server):
        """The `make obs-smoke` contract in one test: traced query →
        /debug/traces renders the tree, /debug/queries empty after the
        run, /metrics Prometheus-parseable."""
        _seed_single(server)
        global_tracer().sample_rate = 1.0
        hdr_resp = req("POST", f"{uri(server)}/index/i/query",
                       b"Count(Row(f=1))")
        assert hdr_resp == {"results": [2]}
        traces = req("GET", f"{uri(server)}/debug/traces")
        assert traces["traces"], "no span tree on /debug/traces"
        assert not req("GET", f"{uri(server)}/debug/queries")["queries"]
        _parse_prometheus(
            req("GET", f"{uri(server)}/metrics", raw=True).decode()
        )

    def test_remote_trace_header_returns_subtree(self, server):
        """An internal hop carrying X-Pilosa-Trace gets the span subtree
        in its response envelope even with local sampling OFF — the
        coordinator made the decision."""
        _seed_single(server)
        r = urllib.request.Request(
            f"{uri(server)}/index/i/query?remote=true&shards=0",
            data=b"Count(Row(f=1))", method="POST",
        )
        r.add_header(TRACE_HEADER, "aabbccddeeff0011:112233445566")
        with urllib.request.urlopen(r, timeout=30) as resp:
            out = json.loads(resp.read())
        assert "trace" in out, out
        sub = out["trace"]
        assert sub["traceId"] == "aabbccddeeff0011"
        assert sub["parentId"] == "112233445566"
        assert sub["name"] == "rpc.query"
        _assert_tree_consistent(sub)
