"""Pin the bench_pallas kernel logic in interpret mode (runs on the CPU
backend; the on-chip timing comparison is bench_pallas.py proper)."""

import numpy as np

from bench_pallas import pallas_intersect_count


def test_pallas_kernel_matches_numpy_oracle():
    rows, words, bw = 8, 4096, 512
    fn = pallas_intersect_count(bw, rows=rows, words=words, interpret=True)
    rng = np.random.default_rng(0)
    a = rng.integers(0, 1 << 32, (rows, words), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, (rows, words), dtype=np.uint32)
    for salt in (0, 7):
        got = np.asarray(fn(a, b, np.full(1, salt, np.uint32))).ravel()
        want = np.bitwise_count(a & (b ^ np.uint32(salt))).sum(axis=1)
        assert np.array_equal(got.astype(np.int64), want.astype(np.int64))
