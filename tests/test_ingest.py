"""Parallel ingest pipeline (ISSUE 3): vectorized routing, concurrent
shard/replica fan-out with per-node error capture + retry, bounded local
shard-group apply, streaming CLI import with server-limit clamping, and
the ingest_* observability series.

The in-process fake-transport tests mirror the reference's unit strategy
for api.Import routing: a real Cluster object computes ownership, the
InternalClient is swapped for an injectable transport (delays, faults,
call capture) so fan-out timing and partial-failure semantics are
assertable without sockets. The replica-consistency test runs REAL HTTP
servers via cluster_helpers.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from cluster_helpers import make_cluster, req, uri
from pilosa_tpu.parallel.client import ClientError
from pilosa_tpu.parallel.cluster import Cluster, Node
from pilosa_tpu.server.api import API, ImportRoutingError
from pilosa_tpu.shardwidth import SHARD_WIDTH, SHARD_WIDTH_EXP
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.storage.view import VIEW_STANDARD


class FakeTransport:
    """Injectable InternalClient stand-in: records every import call,
    applies an optional per-uri delay, and fails a per-uri budget of
    calls with a configurable ClientError status (None = transport-level
    node fault)."""

    def __init__(self, delays=None, fail=None):
        self.delays = dict(delays or {})
        # uri -> [remaining failures, status]
        self.fail = {u: list(v) for u, v in (fail or {}).items()}
        self.calls = []
        self.lock = threading.Lock()

    def _hit(self, kind, uri, payload, n):
        with self.lock:
            self.calls.append((kind, uri, payload))
        delay = self.delays.get(uri, 0)
        if delay:
            time.sleep(delay)
        budget = self.fail.get(uri)
        if budget and budget[0] > 0:
            budget[0] -= 1
            raise ClientError(f"injected fault on {uri}", status=budget[1])
        return n

    def import_bits(self, uri, index, field, rows, columns,
                    timestamps=None, clear=False):
        payload = (np.asarray(rows).tolist(), np.asarray(columns).tolist(),
                   timestamps, clear)
        return self._hit("bits", uri, payload, len(columns))

    def import_values(self, uri, index, field, columns, values, clear=False):
        payload = (np.asarray(columns).tolist(),
                   np.asarray(values).tolist(), clear)
        return self._hit("values", uri, payload, len(columns))

    def import_roaring(self, uri, index, field, shard, data):
        from pilosa_tpu.roaring.format import load_any

        bm, _ = load_any(data)
        ids = bm.to_ids()
        return self._hit("roaring", uri, (shard, ids.tolist()),
                         int(ids.size))

    def send_message(self, uri, message):
        return {}


def fake_cluster(tmp_path, n_peers=3, replica_n=1, delays=None, fail=None):
    holder = Holder(str(tmp_path / "local")).open()
    api = API(holder)
    cluster = Cluster(
        Node("n0", "http://n0"),
        peers=[Node(f"n{i}", f"http://n{i}") for i in range(1, n_peers + 1)],
        replica_n=replica_n, holder=holder,
    )
    cluster.api = api
    api.cluster = cluster
    transport = FakeTransport(delays=delays, fail=fail)
    cluster.client = transport
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("m", FieldOptions(type="mutex"))
    idx.create_field("v", FieldOptions(type="int", min=0, max=10_000))
    return holder, api, cluster, transport


def spread_columns(n_shards=12, per_shard=8):
    return np.concatenate([
        s * SHARD_WIDTH + np.arange(per_shard) for s in range(n_shards)
    ]).astype(np.int64)


# ------------------------------------------------------------ routing


def test_routed_destinations_match_ownership(tmp_path):
    """Every column of a routed batch lands exactly on its shard's
    owners: local portion applied locally, each remote owner's slice
    shipped once — including replicas (replica_n=2) and the non-roaring
    mutex route."""
    holder, api, cluster, transport = fake_cluster(tmp_path, n_peers=2,
                                                   replica_n=2)
    try:
        cols = spread_columns()
        rows = (cols % 5).astype(np.int64)
        changed = api.import_bits("i", "m", rows, cols)
        # oracle: per-column owner set from the cluster ring
        want = {}  # node id -> set of columns
        for c in cols.tolist():
            for node in cluster.shard_nodes("i", c >> SHARD_WIDTH_EXP):
                want.setdefault(node.id, set()).add(c)
        got = {}
        for kind, u, payload in transport.calls:
            assert kind == "bits"  # mutex fields must NOT ride roaring
            got.setdefault(u.rsplit("/")[-1], set()).update(payload[1])
        for node_id, want_cols in want.items():
            if node_id == "n0":
                frag_cols = set()
                view = holder.index("i").field("m").view(VIEW_STANDARD)
                for shard, frag in view.fragments.items():
                    base = shard << SHARD_WIDTH_EXP
                    for r in frag.row_ids():
                        frag_cols.update(
                            base + int(p) for p in frag.row_columns(r)
                        )
                assert frag_cols == want_cols
            else:
                assert got[node_id] == want_cols
        # changed = locally applied bits + every remote ack
        acked = sum(len(p[1]) for _, _, p in transport.calls)
        assert changed == len(want.get("n0", ())) + acked
    finally:
        holder.close()


def test_routed_set_batches_ride_roaring(tmp_path):
    holder, api, cluster, transport = fake_cluster(tmp_path)
    try:
        cols = spread_columns()
        api.import_bits("i", "f", np.ones(cols.size, np.int64), cols)
        kinds = {k for k, _, _ in transport.calls}
        assert kinds == {"roaring"}
    finally:
        holder.close()


def test_routed_fanout_wall_tracks_slowest_node(tmp_path):
    """Acceptance: with an injected per-node delay, routed-import wall
    time tracks the MAX of per-node latencies, not the sum."""
    delay = 0.15
    holder, api, cluster, transport = fake_cluster(
        tmp_path, n_peers=3,
        delays={f"http://n{i}": delay for i in (1, 2, 3)},
    )
    try:
        # exactly ONE column per remote owner -> one delayed call each
        per_node = {}
        shard = 0
        while len(per_node) < 3:
            owner = cluster.shard_nodes("i", shard)[0]
            if owner.id != "n0" and owner.id not in per_node:
                per_node[owner.id] = shard
            shard += 1
        cols = np.asarray(
            [s * SHARD_WIDTH for s in per_node.values()], np.int64
        )
        t0 = time.perf_counter()
        changed = api.import_bits("i", "f", np.ones(cols.size, np.int64),
                                  cols)
        wall = time.perf_counter() - t0
        assert changed == cols.size
        assert len(transport.calls) == 3
        # serial fan-out would cost >= 3 * delay; concurrent ~ delay
        assert wall < 2 * delay, f"fan-out serialized: {wall:.3f}s"
    finally:
        holder.close()


def test_routed_import_retries_once_on_node_fault(tmp_path):
    from pilosa_tpu.utils.stats import global_stats

    holder, api, cluster, transport = fake_cluster(
        tmp_path, fail={"http://n1": [1, None]},  # first call faults
    )
    try:
        before = global_stats().snapshot()["counters"].get(
            'ingest_retries{node="n1"}', 0
        )
        cols = spread_columns()
        changed = api.import_bits("i", "f", np.ones(cols.size, np.int64),
                                  cols)
        assert changed == cols.size  # retry made the batch whole
        after = global_stats().snapshot()["counters"].get(
            'ingest_retries{node="n1"}', 0
        )
        assert after == before + 1
    finally:
        holder.close()


def test_routed_partial_failure_structured_error(tmp_path):
    """Satellite: per-node error collection — a dead owner surfaces as
    ImportRoutingError naming the node and the count applied on healthy
    owners, instead of aborting mid-loop."""
    holder, api, cluster, transport = fake_cluster(
        tmp_path, fail={"http://n1": [99, None]},  # faults forever
    )
    try:
        cols = spread_columns()
        with pytest.raises(ImportRoutingError) as ei:
            api.import_bits("i", "f", np.ones(cols.size, np.int64), cols)
        err = ei.value
        assert err.failed_nodes == ["n1"]
        assert err.status == 502
        assert "n1" in str(err) and "applied" in str(err)
        # healthy owners' batches still landed (error capture, no abort)
        ok_uris = {u for k, u, _ in transport.calls if u != "http://n1"}
        applied_remote = sum(
            len(p[1]) for k, u, p in transport.calls
            if u != "http://n1" and k == "roaring"
        )
        assert ok_uris  # other nodes were reached
        assert err.applied >= applied_remote > 0
    finally:
        holder.close()


def test_routed_deterministic_4xx_no_retry(tmp_path):
    holder, api, cluster, transport = fake_cluster(
        tmp_path, fail={"http://n1": [99, 400]},
    )
    try:
        cols = spread_columns()
        with pytest.raises(ImportRoutingError) as ei:
            api.import_bits("i", "f", np.ones(cols.size, np.int64), cols)
        assert ei.value.status == 400  # deterministic status propagates
        n1_calls = [c for c in transport.calls if c[1] == "http://n1"]
        # 4xx means the REQUEST is bad on every replay: exactly one
        # attempt per n1 shard batch, no retry
        shards_on_n1 = {p[0] for _, u, p in n1_calls}
        assert len(n1_calls) == len(shards_on_n1)
    finally:
        holder.close()


def test_routed_values_and_timestamps_slices(tmp_path):
    """Value batches and timestamped bit batches carry correctly sliced
    payloads per node (vectorized routing must not scramble the
    row/col/ts/value alignment)."""
    holder, api, cluster, transport = fake_cluster(tmp_path)
    try:
        cols = spread_columns(n_shards=6)
        vals = (cols // SHARD_WIDTH + 7).astype(np.int64)
        api.import_values("i", "v", cols, vals)
        for kind, u, (pc, pv, clear) in transport.calls:
            assert kind == "values" and not clear
            assert pv == [(c >> SHARD_WIDTH_EXP) + 7 for c in pc]
        transport.calls.clear()
        # timestamped bits take the import_bits route with aligned ts
        idx = holder.index("i")
        idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
        ts = [f"2020-01-{1 + (int(c) % 9):02d}" for c in cols]
        api.import_bits("i", "t", np.ones(cols.size, np.int64), cols,
                        timestamps=ts)
        by_col = dict(zip(cols.tolist(), ts))
        for kind, u, (pr, pc, pts, clear) in transport.calls:
            assert kind == "bits"
            assert pts == [by_col[c] for c in pc]
    finally:
        holder.close()


# ----------------------------------------------------- local parallel apply


def _import_with_workers(tmp_path, name, workers, cols, rows, ts=None):
    holder = Holder(str(tmp_path / name)).open()
    api = API(holder)
    api.ingest_workers = workers
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
    changed = api.import_bits("i", "f", rows, cols)
    changed_t = api.import_bits("i", "t", rows, cols, timestamps=ts)
    sig = {}
    for fname in ("f", "t", "_exists"):
        field = idx.field(fname)
        for vname, view in field.views.items():
            for s, frag in view.fragments.items():
                sig[(fname, vname, s)] = frag.serialize_snapshot()
    holder.close()
    return changed, changed_t, sig


def test_parallel_local_apply_matches_serial(tmp_path):
    """ingest-workers > 1 must be byte-identical to serial apply across
    data fragments, generated time views, and the existence field."""
    rng = np.random.default_rng(3)
    cols = np.sort(rng.choice(8 * SHARD_WIDTH, 4000, replace=False)
                   ).astype(np.int64)
    rows = (cols % 4).astype(np.int64)
    ts = [f"2021-0{1 + (int(c) % 8)}-03" if c % 3 else None
          for c in cols.tolist()]
    serial = _import_with_workers(tmp_path, "serial", 1, cols, rows, ts)
    parallel = _import_with_workers(tmp_path, "par", 4, cols, rows, ts)
    assert serial[0] == parallel[0] and serial[1] == parallel[1]
    assert serial[2] == parallel[2]


def test_ingest_workers_config_knob(tmp_path):
    from pilosa_tpu.server import Server, ServerConfig

    cfg = ServerConfig.from_dict({"ingest-workers": "3"})
    assert cfg.ingest_workers == 3
    server = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, name="w",
        anti_entropy_interval=0, heartbeat_interval=0, ingest_workers=2,
    )).open()
    try:
        assert server.api.ingest_workers == 2
    finally:
        server.close()


# ------------------------------------------------- replica consistency


def test_concurrent_routed_imports_replicas_identical(tmp_path):
    """Acceptance: after concurrent import_bits/import_values from
    several client threads (including the mutex non-roaring route),
    every replicated fragment is byte-identical across nodes."""
    servers = make_cluster(tmp_path, 2, replica_n=2)
    try:
        base = [uri(s) for s in servers]
        req("POST", f"{base[0]}/index/i", {})
        req("POST", f"{base[0]}/index/i/field/f", {})
        req("POST", f"{base[0]}/index/i/field/m",
            {"options": {"type": "mutex"}})
        req("POST", f"{base[0]}/index/i/field/v",
            {"options": {"type": "int", "min": 0, "max": 100000}})
        n_shards, per_thread = 4, 60
        errors = []

        def writer(t):
            try:
                # disjoint columns per thread: mutex writes to one
                # column from two threads are racy by definition
                cols = [s * SHARD_WIDTH + t * per_thread + k
                        for s in range(n_shards)
                        for k in range(per_thread)]
                host = base[t % 2]
                req("POST", f"{host}/index/i/field/f/import",
                    {"rows": [t] * len(cols), "columns": cols})
                req("POST", f"{host}/index/i/field/m/import",
                    {"rows": [t % 3] * len(cols), "columns": cols})
                req("POST", f"{host}/index/i/field/v/import-value",
                    {"columns": cols,
                     "values": [c % 997 for c in cols]})
            except Exception as e:  # surfaced after join
                errors.append(repr(e))

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        # with replica_n == len(nodes) == 2, every fragment must exist
        # on both nodes with byte-identical serialized content
        for field, view in (("f", "standard"), ("m", "standard"),
                            ("v", "bsig_v")):
            for shard in range(n_shards):
                payloads = [
                    req("GET",
                        f"{b}/internal/fragment/data?index=i&field={field}"
                        f"&view={view}&shard={shard}", raw=True)
                    for b in base
                ]
                assert payloads[0] == payloads[1], (field, view, shard)
                assert payloads[0]  # non-empty: data actually landed
        # spot-check query-level agreement too
        counts = {req("POST", f"{b}/index/i/query",
                      b"Count(Row(f=0))")["results"][0] for b in base}
        assert len(counts) == 1
    finally:
        for s in servers:
            s.close()


def test_http_import_batch_limit_413(tmp_path):
    from pilosa_tpu.server import Server, ServerConfig

    server = Server(ServerConfig(
        data_dir=str(tmp_path / "d"), port=0, name="lim",
        anti_entropy_interval=0, heartbeat_interval=0,
        max_writes_per_request=8,
    )).open()
    try:
        base = f"http://localhost:{server.port}"
        req("POST", f"{base}/index/i", {})
        req("POST", f"{base}/index/i/field/f", {})
        st = req("GET", f"{base}/status")
        assert st["maxWritesPerRequest"] == 8  # CLI probe surface
        cols = list(range(20))
        with pytest.raises(urllib.error.HTTPError) as ei:
            req("POST", f"{base}/index/i/field/f/import",
                {"rows": [1] * 20, "columns": cols})
        assert ei.value.code == 413
        # remote hops carry slices of an admitted edge batch: exempt
        out = req("POST", f"{base}/index/i/field/f/import?remote=true",
                  {"rows": [1] * 20, "columns": cols})
        assert out["changed"] == 20
    finally:
        server.close()


# --------------------------------------------------------------- CLI


def _boot_server(tmp_path, **kw):
    from pilosa_tpu.server import Server, ServerConfig

    return Server(ServerConfig(
        data_dir=str(tmp_path / "srv"), port=0, name="cli",
        anti_entropy_interval=0, heartbeat_interval=0, **kw,
    )).open()


def test_cli_import_clamps_batch_to_server_limit(tmp_path, capsys):
    """Satellite: the CLI probes /status and clamps its HTTP batches to
    max-writes-per-request instead of bouncing 100k-row bodies."""
    from pilosa_tpu.cli import main

    server = _boot_server(tmp_path, max_writes_per_request=16)
    try:
        csv = tmp_path / "bits.csv"
        csv.write_text("".join(f"1,{c}\n" for c in range(100)))
        rc = main(["import", "-i", "i", "-f", "f", "--create",
                   "--host", f"http://localhost:{server.port}", str(csv)])
        assert rc == 0
        assert "100 bits changed" in capsys.readouterr().out
        out = req("POST", f"http://localhost:{server.port}/index/i/query",
                  b"Count(Row(f=1))")
        assert out == {"results": [100]}
    finally:
        server.close()


def test_cli_import_splits_on_413(tmp_path, capsys, monkeypatch):
    """Probe-less fallback: when /status does not advertise the limit,
    oversized batches split in half on 413 until they fit."""
    from pilosa_tpu import cli

    server = _boot_server(tmp_path, max_writes_per_request=8)
    try:
        monkeypatch.setattr(cli, "_probe_batch_limit", lambda host: 0)
        csv = tmp_path / "bits.csv"
        csv.write_text("".join(f"2,{c}\n" for c in range(50)))
        rc = cli.main(["import", "-i", "i", "-f", "f", "--create",
                       "--host", f"http://localhost:{server.port}",
                       "--batch-size", "50", str(csv)])
        assert rc == 0
        assert "50 bits changed" in capsys.readouterr().out
    finally:
        server.close()


def test_cli_import_concurrency_and_values(tmp_path, capsys):
    from pilosa_tpu.cli import main

    server = _boot_server(tmp_path)
    try:
        host = f"http://localhost:{server.port}"
        csv = tmp_path / "vals.csv"
        csv.write_text("".join(f"{c},{c % 50}\n" for c in range(300)))
        rc = main(["import", "-i", "i", "-f", "v", "--create", "--values",
                   "--min", "0", "--max", "100", "--host", host,
                   "--batch-size", "32", "--concurrency", "4", str(csv)])
        assert rc == 0
        out = req("POST", f"{host}/index/i/query", b'Sum(field="v")')
        assert out["results"][0]["value"] == sum(c % 50 for c in range(300))
    finally:
        server.close()


def test_ingest_smoke_cli_end_to_end(tmp_path, capsys):
    """Makefile `ingest-smoke`: a small CSV through `cli.py import`
    against an in-process server, verified by query + export."""
    from pilosa_tpu.cli import main

    server = _boot_server(tmp_path)
    try:
        host = f"http://localhost:{server.port}"
        csv = tmp_path / "smoke.csv"
        lines = [(r, r * 31 + c) for r in range(3) for c in range(40)]
        csv.write_text("".join(f"{r},{c}\n" for r, c in lines))
        rc = main(["import", "-i", "smoke", "-f", "f", "--create",
                   "--host", host, str(csv)])
        assert rc == 0
        assert f"{len(lines)} bits changed" in capsys.readouterr().out
        for row in range(3):
            out = req("POST", f"{host}/index/smoke/query",
                      f"Count(Row(f={row}))".encode())
            assert out == {"results": [40]}
        # ingest_* series must be live on /metrics and /debug/vars
        metrics = req("GET", f"{host}/metrics", raw=True).decode()
        assert "ingest_rows_total" in metrics
        assert "ingest_batch_size" in metrics
        dbg = req("GET", f"{host}/debug/vars")
        assert any(k.startswith("ingest_apply")
                   for k in dbg["distributions"])
    finally:
        server.close()


def test_streaming_csv_iterators(tmp_path):
    from pilosa_tpu.cli import (
        _iter_csv_bits,
        _iter_csv_values,
        _parse_csv_bits,
        _parse_csv_values,
    )

    csv = tmp_path / "b.csv"
    csv.write_text("0,1\n# comment\n1,2,2020-01-01\n\n2,3\n3,4\n")
    batches = list(_iter_csv_bits([str(csv)], 3))
    assert len(batches) == 2
    assert batches[0][0] == [0, 1, 2]
    assert batches[0][2][1] == "2020-01-01"  # ts kept batch-aligned
    assert batches[1] == ([3], [4], None)
    rows, cols, ts = _parse_csv_bits([str(csv)])
    assert rows == [0, 1, 2, 3] and cols == [1, 2, 3, 4]
    vcsv = tmp_path / "v.csv"
    vcsv.write_text("1,10\n2,20\n3,30\n")
    assert list(_iter_csv_values([str(vcsv)], 2)) == [
        ([1, 2], [10, 20]), ([3], [30])
    ]
    assert _parse_csv_values([str(vcsv)]) == ([1, 2, 3], [10, 20, 30])


# --------------------------------------------------------------- stats


def test_stats_quantiles_and_observations():
    from pilosa_tpu.utils.stats import StatsClient

    s = StatsClient(prefix="t")
    for v in range(100):
        s.timing("lat", v / 1000.0)
        s.observe("batch", float(v))
    text = s.prometheus_text()
    assert 't_lat_seconds{quantile="0.5"}' in text
    assert 't_batch{quantile="0.95"}' in text
    assert "t_batch_count 100" in text
    assert abs(s.quantile("lat", 0.5) - 0.0495) < 0.005
    assert abs(s.quantile("batch", 0.95) - 94) <= 2
    snap = s.snapshot()
    assert snap["distributions"]["lat"]["count"] == 100
    assert snap["distributions"]["batch"]["p95"] >= 90
