"""Autopilot placement plane: the override table beside the hash ring,
the pure planner's properties, and the end-to-end move loop.

The contract that keeps mixed configs safe — and that these tests pin
hardest — is byte-identity: with an EMPTY override table (equivalently,
with the autopilot kill switch off, since only the planner mints
entries) every ownership decision must equal the pure hash walk,
bit for bit, across arbitrary memberships."""

import json
import random
import threading

import pytest

from cluster_helpers import make_cluster, req, seed, uri
from pilosa_tpu.autopilot import plan_moves, shaped_move_budget
from pilosa_tpu.autopilot.planner import Autopilot
from pilosa_tpu.parallel.cluster import (
    PARTITION_N,
    Cluster,
    Node,
    PlacementTable,
    _hash64,
)
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _reference_owners(nodes, replica_n, index, shard):
    """The pre-autopilot placement, reimplemented from scratch: ring of
    nodes ordered by (hash64(id), id), walk min(replica_n, n) from the
    partition point."""
    ring = sorted(nodes, key=lambda n: (_hash64(n.id), n.id))
    partition = _hash64(f"{index}:{shard}") % PARTITION_N
    start = partition % len(ring)
    k = min(replica_n, len(ring))
    return [ring[(start + i) % len(ring)].id for i in range(k)]


def _bare_cluster(node_ids, replica_n=1) -> Cluster:
    nodes = [Node(i, f"http://{i}:1") for i in node_ids]
    return Cluster(nodes[0], peers=nodes[1:], replica_n=replica_n)


class TestPlacementFallback:
    def test_empty_table_byte_identical_across_random_memberships(self):
        """The mixed-version safety contract: no overrides ⇒ shard_nodes
        equals the pure hash walk for every (membership, replica_n,
        index, shard) — randomized, seeded."""
        rng = random.Random(1138)
        for _ in range(40):
            n = rng.randint(1, 8)
            ids = rng.sample(
                [f"node-{i}" for i in range(64)] + ["a", "zz", "n0"], n)
            replica_n = rng.randint(1, 3)
            c = _bare_cluster(ids, replica_n=replica_n)
            assert len(c.placement) == 0
            for _ in range(25):
                index = rng.choice(["i", "tenants", "x-y"])
                shard = rng.randint(0, 5000)
                got = [x.id for x in c.shard_nodes(index, shard)]
                assert got == _reference_owners(
                    list(c.nodes.values()), replica_n, index, shard)

    def test_kill_switch_off_server_mints_nothing(self, tmp_path):
        """autopilot-enabled=false (the default): no planner is wired
        and the table stays empty, so placement is the hash walk."""
        servers = make_cluster(tmp_path, 2, replica_n=1)
        try:
            for s in servers:
                c = s.api.cluster
                assert s.api.autopilot is None
                assert len(c.placement) == 0 and c.placement.epoch == 0
                for shard in range(8):
                    got = [x.id for x in c.shard_nodes("i", shard)]
                    assert got == _reference_owners(
                        list(c.nodes.values()), 1, "i", shard)
            out = req("GET", f"{uri(servers[0])}/debug/autopilot")
            assert out["enabled"] is False
            assert out["placement"] == {"epoch": 0, "overrides": []}
        finally:
            for s in servers:
                s.close()

    def test_override_applies_only_while_all_owners_live(self):
        c = _bare_cluster(["n0", "n1", "n2"], replica_n=2)
        hash_owners = [x.id for x in c.shard_nodes("i", 3)]
        override = tuple(
            i for i in ("n0", "n1", "n2") if i not in hash_owners
        )[:1] + (hash_owners[0],)
        c.placement.replace({("i", 3): override}, epoch=10)
        assert [x.id for x in c.shard_nodes("i", 3)] == list(override)
        # a listed owner departs: hash placement resumes for the shard
        with c._lock:
            c.nodes.pop(override[0])
            c._note_membership_changed_locked()
        assert [x.id for x in c.shard_nodes("i", 3)] == \
            _reference_owners(list(c.nodes.values()), 2, "i", 3)
        # other shards were never overridden
        assert [x.id for x in c.shard_nodes("i", 4)] == \
            _reference_owners(list(c.nodes.values()), 2, "i", 4)

    def test_stale_epoch_loses(self):
        t = PlacementTable()
        assert t.replace({("i", 0): ("a",)}, epoch=5)
        assert not t.replace({("i", 0): ("b",)}, epoch=5)  # duplicate
        assert not t.replace({("i", 0): ("b",)}, epoch=4)  # stale
        assert t.get("i", 0) == ("a",)
        assert t.replace({("i", 0): ("b",)}, epoch=6)
        assert t.get("i", 0) == ("b",)
        assert t.updates_applied == 2 and t.updates_rejected == 2

    def test_wire_round_trip_skips_malformed(self):
        table = {("i", 0): ("a", "b"), ("j", 7): ("c",)}
        entries = PlacementTable.wire_entries(table)
        assert PlacementTable.from_wire(entries) == table
        entries.append({"index": "k"})             # no shard
        entries.append({"shard": 1, "nodes": []})  # no index
        entries.append("garbage")
        assert PlacementTable.from_wire(entries) == table

    def test_persistence_and_corrupt_file_recovery(self, tmp_path):
        path = str(tmp_path / "cluster.placement")
        t = PlacementTable(path=path)
        t.replace({("i", 2): ("a", "b")}, epoch=9)
        reloaded = PlacementTable(path=path)
        assert reloaded.epoch == 9
        assert reloaded.get("i", 2) == ("a", "b")
        with open(path, "w") as f:
            f.write("{torn write")
        assert PlacementTable(path=path).epoch == 0  # empty, not fatal

    def test_placement_update_message_is_epoch_fenced(self):
        c = _bare_cluster(["n0", "n1"])
        wire = PlacementTable.wire_entries({("i", 0): ("n1",)})
        c.adopt_epoch(5000)
        # stale fenced message: rejected before adoption
        c.handle_message({"type": "placement-update", "epoch": 400,
                          "overrides": wire})
        assert c.placement.epoch == 0
        c.handle_message({"type": "placement-update", "epoch": 6000,
                          "overrides": wire})
        assert c.placement.epoch == 6000
        assert c.placement.get("i", 0) == ("n1",)

    def test_status_gossip_rides_only_when_minted(self, tmp_path):
        servers = make_cluster(tmp_path, 2, replica_n=1)
        try:
            st = req("GET", f"{uri(servers[0])}/status")
            assert "placement" not in st  # empty table: legacy wire shape
            c0 = servers[0].api.cluster
            epoch = c0.apply_placement(
                {("i", 0): (servers[1].api.cluster.local.id,)})
            assert epoch > 0
            st = req("GET", f"{uri(servers[0])}/status")
            assert st["placement"]["epoch"] == epoch
            assert st["placement"]["overrides"] == [
                {"index": "i", "shard": 0,
                 "nodes": [servers[1].api.cluster.local.id]}]
        finally:
            for s in servers:
                s.close()


class TestRingMemo:
    def test_memoized_ring_tracks_membership_churn(self):
        c = _bare_cluster(["n0", "n1", "n2"])
        c._spawn_resize = lambda: None  # no wire in this unit test
        ring1 = c._frozen_ring()
        assert c._frozen_ring() is ring1  # cache hit: same object
        assert [n.id for n in ring1] == [
            n.id for n in sorted(c.nodes.values(),
                                 key=lambda n: (_hash64(n.id), n.id))]
        c.handle_message({"type": "node-join", "id": "n3",
                          "uri": "http://n3:1"})
        ring2 = c._frozen_ring()
        assert ring2 is not ring1
        assert {n.id for n in ring2} == {"n0", "n1", "n2", "n3"}
        assert [n.id for n in ring2] == [
            n.id for n in sorted(c.nodes.values(),
                                 key=lambda n: (_hash64(n.id), n.id))]
        c.handle_message({"type": "node-leave", "id": "n1",
                          "epoch": c.epoch})
        ring3 = c._frozen_ring()
        assert {n.id for n in ring3} == {"n0", "n2", "n3"}
        assert c._frozen_ring() is ring3

    def test_hash_memo_is_bounded(self):
        c = _bare_cluster(["n0"])
        c._ring_hash_memo.update(
            {f"x{i}": i for i in range(5000)})
        with c._lock:
            c._note_membership_changed_locked()
        c._frozen_ring()
        assert len(c._ring_hash_memo) <= 4096


class TestPlannerProperties:
    def _owners_from(self, table):
        return lambda i, s: list(table[(i, s)])

    def test_uniform_heat_plans_zero_moves(self):
        rng = random.Random(7)
        for n in (2, 3, 5, 8):
            nodes = [f"n{i}" for i in range(n)]
            table, heat = {}, {}
            for s in range(n * 6):
                key = ("i", s)
                table[key] = [nodes[s % n]]
                heat[key] = 10.0
            for budget in (1.2, 1.5, 3.0):
                assert plan_moves(
                    heat, self._owners_from(table), nodes,
                    heat_budget=budget, max_moves=8) == []
            # jitter within the dead band is also quiescent
            jittered = {k: v * rng.uniform(0.95, 1.05)
                        for k, v in heat.items()}
            assert plan_moves(
                jittered, self._owners_from(table), nodes,
                heat_budget=1.5, max_moves=8) == []

    def test_hot_spot_drains_and_replan_is_idempotent(self):
        nodes = ["n0", "n1", "n2"]
        table = {("i", s): [nodes[s % 3]] for s in range(12)}
        heat = {k: 1.0 for k in table}
        for s in (0, 3, 6, 9):  # all of n0's shards run hot
            heat[("i", s)] = 80.0
        moves = plan_moves(heat, self._owners_from(table), nodes,
                           heat_budget=1.3, max_moves=8)
        assert moves, "overloaded node must shed"
        assert all(m["from"] == "n0" for m in moves)
        for m in moves:
            table[(m["index"], m["shard"])] = list(m["owners"])
            assert "n0" not in m["owners"]
        # idempotent fixpoint: applying the plan leaves nothing to do
        assert plan_moves(heat, self._owners_from(table), nodes,
                          heat_budget=1.3, max_moves=8) == []

    def test_frozen_keys_are_immune(self):
        nodes = ["n0", "n1"]
        table = {("i", 0): ["n0"], ("i", 1): ["n1"]}
        heat = {("i", 0): 100.0, ("i", 1): 1.0}
        assert plan_moves(heat, self._owners_from(table), nodes,
                          heat_budget=1.2, max_moves=4,
                          frozen={("i", 0)}) == []

    def test_replicated_groups_move_one_owner(self):
        nodes = ["n0", "n1", "n2", "n3"]
        table = {("i", s): ["n0", "n1"] for s in range(4)}
        heat = {k: 40.0 for k in table}
        moves = plan_moves(heat, self._owners_from(table), nodes,
                           heat_budget=1.3, max_moves=8)
        assert moves
        for m in moves:
            assert len(m["owners"]) == 2
            assert len(set(m["owners"])) == 2  # never twice on one node
            assert m["to"] in ("n2", "n3")

    def test_never_moves_onto_hotter_node(self):
        """Two nodes, one hot indivisible group: relocating it would
        just move the hot spot — the planner must refuse."""
        nodes = ["n0", "n1"]
        table = {("i", 0): ["n0"], ("i", 1): ["n1"]}
        heat = {("i", 0): 100.0, ("i", 1): 10.0}
        assert plan_moves(heat, self._owners_from(table), nodes,
                          heat_budget=1.2, max_moves=4) == []

    def test_degenerate_inputs(self):
        assert plan_moves({}, lambda i, s: [], ["n0", "n1"]) == []
        assert plan_moves({("i", 0): 5.0}, lambda i, s: ["n0"],
                          ["n0"]) == []          # single node
        assert plan_moves({("i", 0): 5.0}, lambda i, s: ["n0"],
                          ["n0", "n1"], max_moves=0) == []
        # owners outside the live membership contribute nothing
        assert plan_moves({("i", 0): 5.0}, lambda i, s: ["ghost"],
                          ["n0", "n1"]) == []

    def test_shaped_move_budget(self):
        class Pacer:
            def __init__(self, rate):
                self.rate = rate

        assert shaped_move_budget(8, None, 30.0) == 8       # unpaced
        assert shaped_move_budget(8, Pacer(0), 30.0) == 8
        # 2 MiB/s × 1 s / 1 MiB nominal = 2 moves
        assert shaped_move_budget(8, Pacer(2 << 20), 1.0) == 2
        # pacer never zeroes a nonzero configured budget
        assert shaped_move_budget(8, Pacer(1), 1.0) == 1
        assert shaped_move_budget(0, Pacer(2 << 20), 1.0) == 0


class TestAutopilotEndToEnd:
    N_SHARDS = 6

    def test_pass_moves_hot_shards_and_data_survives(self, tmp_path):
        from pilosa_tpu.storage.heat import global_heat

        servers = make_cluster(tmp_path, 2, replica_n=1)
        ap = None
        try:
            s0, s1 = servers
            seed(s0, n_shards=self.N_SHARDS)
            coord = s0 if s0.api.cluster.is_acting_coordinator else s1
            ap = Autopilot(coord.api.cluster, heat=global_heat(),
                           slo=coord.api.slo, interval_s=0.0,
                           heat_budget=1.2, max_moves=4)
            rec = ap.run_pass()
            # seeding skews heat toward whichever node owned more
            # shards; whether the pass acts depends on the hash layout —
            # but acting or not, placement must stay consistent and the
            # data fully queryable from BOTH nodes
            if rec.get("acted"):
                assert coord.api.cluster.placement.epoch == rec["epoch"]
                assert s1.api.cluster.placement.epoch == \
                    s0.api.cluster.placement.epoch
                assert ap.moves_executed == len(rec["moves"])
            for s in servers:
                assert s.api.cluster.wait_until_normal(10)
                out = req("POST", f"{uri(s)}/index/i/query",
                          b"Count(Row(f=1))")
                assert out["results"][0] == self.N_SHARDS * 4
                out = req("POST", f"{uri(s)}/index/i/query",
                          b"Count(Intersect(Row(f=1), Row(f=2)))")
                assert out["results"][0] == self.N_SHARDS * 2
            # both nodes agree on every shard's owner
            for shard in range(self.N_SHARDS):
                assert [n.id for n in
                        s0.api.cluster.shard_nodes("i", shard)] == \
                       [n.id for n in
                        s1.api.cluster.shard_nodes("i", shard)]
        finally:
            if ap is not None:
                ap.close()
            for s in servers:
                s.close()

    def test_forced_override_executes_through_resize(self, tmp_path):
        """Drive the actuator directly: force every shard onto one node
        via apply_placement + coordinate_resize, then verify the mover
        now owns them, queries still answer, and a kill-switch-off peer
        adopted the table."""
        servers = make_cluster(tmp_path, 2, replica_n=1)
        try:
            s0, s1 = servers
            seed(s0, n_shards=self.N_SHARDS)
            c0 = s0.api.cluster
            # force everything onto the node the hash gave the FEWEST
            # shards, so the override genuinely moves data
            owned = {nid: 0 for nid in c0.nodes}
            for s in range(self.N_SHARDS):
                owned[c0.shard_nodes("i", s)[0].id] += 1
            target = min(owned, key=owned.get)
            hash_owned_by_target = owned[target]
            table = {("i", s): (target,) for s in range(self.N_SHARDS)}
            epoch = c0.apply_placement(table)
            assert epoch > 0
            c0.coordinate_resize()
            assert c0.wait_until_normal(15)
            assert s1.api.cluster.wait_until_normal(15)
            # both nodes route every shard to the target now
            for c in (c0, s1.api.cluster):
                for s in range(self.N_SHARDS):
                    assert [n.id for n in c.shard_nodes("i", s)] == \
                        [target]
            assert s1.api.cluster.placement.epoch == epoch
            # the target node sees itself as owner of every shard
            mover = next(s for s in servers
                         if s.api.cluster.local.id == target)
            assert all(mover.api.cluster.owns_shard("i", s)
                       for s in range(self.N_SHARDS))
            for srv in servers:
                out = req("POST", f"{uri(srv)}/index/i/query",
                          b"Count(Row(f=1))")
                assert out["results"][0] == self.N_SHARDS * 4
            # sanity: the move was real for at least one shard
            assert hash_owned_by_target < self.N_SHARDS
        finally:
            for s in servers:
                s.close()

    def test_pass_gates(self, tmp_path):
        from pilosa_tpu.storage.heat import HeatMap

        c = _bare_cluster(["n0"])
        ap = Autopilot(c, heat=HeatMap(), interval_s=0.0)
        assert ap.run_pass() == {"acted": False, "reason": "single-node"}
        c2 = _bare_cluster(["n0", "n1"])
        c2.is_acting_coordinator  # n0 may or may not coordinate
        ap2 = Autopilot(c2, heat=HeatMap(), interval_s=0.0)
        ap2.cluster.degraded = True
        if c2.is_acting_coordinator:
            assert ap2.run_pass()["reason"] == "degraded"
        else:
            assert ap2.run_pass()["reason"] == "not-coordinator"
        assert ap2.metrics()["autopilot_passes_skipped_total"] >= 1

    def test_dwell_freezes_moved_shards(self):
        from pilosa_tpu.storage.heat import HeatMap

        c = _bare_cluster(["n0", "n1"])
        ap = Autopilot(c, heat=HeatMap(), interval_s=10.0)
        assert ap.min_dwell_s == 20.0  # default: two intervals
        ap._moved_at[("i", 0)] = __import__("time").monotonic()
        moves = plan_moves(
            {("i", 0): 100.0, ("i", 1): 1.0},
            lambda i, s: ["n0"] if s == 0 else ["n1"],
            ["n0", "n1"], heat_budget=1.2, max_moves=4,
            frozen={k for k, t in ap._moved_at.items()})
        assert moves == []


class TestHeatMerge:
    def test_merge_dedups_shared_map_and_sums_scopes(self):
        from pilosa_tpu.storage.heat import merge_shard_heat

        row = {"scope": "a", "index": "i", "field": "f", "shard": 0,
               "access": 5.0, "writes": 1.0}
        # the same global map polled twice (in-process cluster): exact
        # dedup by max, not doubling
        assert merge_shard_heat([[row], [dict(row)]]) == {("i", 0): 6.0}
        other_scope = dict(row, scope="b", access=2.0, writes=0.0)
        out = merge_shard_heat([[row], [other_scope]])
        assert out == {("i", 0): 8.0}  # distinct nodes sum
        # field-level rows sum into the (index, shard) group
        f2 = dict(row, field="g", access=1.0, writes=0.0)
        assert merge_shard_heat([[row, f2]]) == {("i", 0): 7.0}
        # malformed rows are skipped, not fatal
        assert merge_shard_heat([[{"index": "i"}, row, None]]) == \
            {("i", 0): 6.0}


class TestDebugSurface:
    def test_debug_autopilot_and_metrics(self, tmp_path):
        servers = make_cluster(tmp_path, 2, replica_n=1,
                               autopilot_enabled=True,
                               autopilot_interval=3600.0)
        try:
            s0 = servers[0]
            assert s0.api.autopilot is not None
            out = req("GET", f"{uri(s0)}/debug/autopilot")
            assert out["enabled"] is True
            assert out["heatBudget"] == 1.5 and out["maxMoves"] == 4
            assert out["minDwellS"] == 7200.0
            assert "placement" in out and "decisions" in out
            body = req("GET", f"{uri(s0)}/metrics", raw=True).decode()
            for series in ("autopilot_passes_total",
                           "autopilot_moves_executed_total",
                           "autopilot_placement_overrides",
                           "autopilot_placement_epoch"):
                assert series in body
            snap = req("GET", f"{uri(s0)}/debug/vars")
            assert "autopilot_passes_total" in snap["autopilot"]
            m = req("GET", f"{uri(s0)}/debug/vars")["cluster"]
            assert "cluster_placement_overrides" in m
        finally:
            for s in servers:
                s.close()
