"""Multi-host worker: one process of a 2-process CPU-backend cluster.

Launched by tests/test_multihost.py with JAX_PLATFORMS=cpu and 4 virtual
devices per process. Each process opens an identical holder, joins the
global mesh via initialize_distributed, and drives the SAME query
sequence through a DistExecutor (the SPMD contract: every host executes
every query; each host decodes and uploads ONLY the shard slots its
devices own — ShardAssignment.local_slots). Results are replicated
scalars, asserted against a host oracle computed from the same
deterministic data.

Usage: python multihost_worker.py <coordinator_port> <process_id>
"""

import sys
import tempfile

COORD_PORT, PROC_ID = int(sys.argv[1]), int(sys.argv[2])

import jax  # noqa: E402

from pilosa_tpu.parallel.mesh import initialize_distributed  # noqa: E402

initialize_distributed(
    coordinator=f"127.0.0.1:{COORD_PORT}", num_processes=2,
    process_id=PROC_ID,
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()

from pilosa_tpu.parallel.dist import DistExecutor  # noqa: E402
from pilosa_tpu.parallel.mesh import make_mesh  # noqa: E402
from pilosa_tpu.shardwidth import SHARD_WIDTH  # noqa: E402
from pilosa_tpu.storage import FieldOptions, Holder  # noqa: E402

N_SHARDS = 8


def build(holder):
    """Deterministic dataset spanning N_SHARDS shards; returns the
    python-set oracle {row: set(cols)} and {col: value}."""
    idx = holder.create_index("repos", track_existence=False)
    f = idx.create_field("f")
    rows = {1: set(), 2: set(), 3: set()}
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        for k in range(40):
            rows[1].add(base + 7 * k)
            if k % 2 == 0:
                rows[2].add(base + 7 * k)
            if k < 30:  # distinct row sizes: TopN ordering is exact
                rows[3].add(base + 11 * k + 1)
    for row, cols in rows.items():
        for c in sorted(cols):
            f.set_bit(row, c)
    v = idx.create_field("v", FieldOptions(type="int", min=0, max=1000))
    values = {}
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        for k in range(10):
            values[base + 13 * k] = (shard * 31 + k * 7) % 1000
    for c, val in values.items():
        v.set_value(c, val)
    return rows, values


with tempfile.TemporaryDirectory() as tmp:
    holder = Holder(tmp).open()
    rows, values = build(holder)
    ex = DistExecutor(holder, make_mesh())

    got = ex.execute("repos", "Count(Intersect(Row(f=1), Row(f=2)))")[0]
    want = len(rows[1] & rows[2])
    assert got == want, (got, want)

    got = ex.execute("repos", "Count(Union(Row(f=1), Row(f=3)))")[0]
    want = len(rows[1] | rows[3])
    assert got == want, (got, want)

    (s,) = ex.execute("repos", 'Sum(field="v")')
    assert (s.value, s.count) == (sum(values.values()), len(values)), s

    # TopN: phase-1 candidate counts via cross-host countrows psum,
    # phase-2 exact recount — row sizes are distinct by construction
    (pairs,) = ex.execute("repos", "TopN(f, n=2)")
    sizes = sorted(((len(c), r) for r, c in rows.items()), reverse=True)
    got = [(p.id, p.count) for p in pairs]
    want = [(r, n) for n, r in sizes[:2]]
    assert got == want, (got, want)

    # GroupBy over one dimension, cross-host reduced
    (groups,) = ex.execute("repos", "GroupBy(Rows(f))")
    got = {g.group[0]["rowID"]: g.count for g in groups}
    assert got == {r: len(c) for r, c in rows.items()}, got

    # write-through: the contract is that a shard's write is applied on
    # (at least) the process owning that shard's slot; here both
    # replicated holders apply it, which covers the owner. Resident
    # sharded leaves are PATCHED per addressable piece (VERDICT r3 #6:
    # batch._patch_sharded, a single-device scatter + handle reassembly,
    # no collective) — asserted via residency counters: the write must
    # bump `updates` and the re-query must re-decode nothing.
    from pilosa_tpu.storage import residency  # noqa: E402

    cache = residency.global_row_cache()
    misses_before = cache.misses
    updates_before = cache.updates
    new_col = 5 * SHARD_WIDTH + 997  # shard 5: process 1's half
    holder.index("repos").field("f").set_bit(1, new_col)
    holder.index("repos").field("f").set_bit(2, new_col)
    if PROC_ID == 1:  # shard 5's slot is addressable on process 1 only
        assert cache.updates >= updates_before + 2, (
            "multi-host write did not patch resident leaves in place",
            updates_before, cache.updates,
        )
    else:  # non-owner: nothing local to patch, and nothing purged
        assert cache.updates == updates_before, (
            updates_before, cache.updates,
        )
    got = ex.execute("repos", "Count(Intersect(Row(f=1), Row(f=2)))")[0]
    want = len((rows[1] | {new_col}) & (rows[2] | {new_col}))
    assert got == want, (got, want)
    assert cache.misses == misses_before, (
        "write purged resident leaves: re-query re-decoded",
        misses_before, cache.misses,
    )

    holder.close()

print(f"MULTIHOST_WORKER_{PROC_ID}_OK", flush=True)
