"""Hierarchical reduction plane (parallel/reduction.py + the 2-D mesh).

Three contracts, gated here and again (at scale, with records) by the
bench_suite ``mesh`` config:

* bit-exactness — every reduce kind on every mesh factorization returns
  byte-identical results to the single-device Executor, including
  non-divisible shard counts (padded slots);
* the wire model — dense-equivalent vs actual reduction-lane bytes are
  recorded per dispatch, actual is smaller on hierarchical meshes, and
  Row/TopN shapes clear the ≥4x bar the ROADMAP target needs;
* the experimental-fallback guard — concurrent dispatches from
  executors over DIFFERENT meshes serialize instead of deadlocking when
  shard_map comes from jax.experimental.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.result import result_to_json
from pilosa_tpu.parallel import DistExecutor, make_mesh, mesh_groups
from pilosa_tpu.parallel import dist as dist_mod
from pilosa_tpu.parallel import reduction
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.utils import cost as cost_mod

N_SHARDS = 13  # deliberately not a multiple of any mesh size

# mesh sizes 1/2/4/8 including 2-D groups x shards factorizations
MESH_CONFIGS = [(1, None), (2, None), (2, 2), (4, 2), (8, 2), (8, 4)]

# one query per reduce kind: count, row, bsisum, min, max,
# countrows (TopN), groupby + aggregate
KIND_QUERIES = [
    "Count(Row(f=1))",
    "Union(Row(f=2), Row(g=3))",
    "Sum(Row(f=1), field=fare)",
    "Min(field=fare)",
    "Max(field=fare)",
    "TopN(f, n=2)",
    "GroupBy(Rows(f), aggregate=Sum(field=fare))",
]


@pytest.fixture(scope="module")
def holder(tmp_path_factory):
    holder = Holder(str(tmp_path_factory.mktemp("mesh") / "data")).open()
    idx = holder.create_index("big")
    f = idx.create_field("f")
    g = idx.create_field("g")
    fare = idx.create_field("fare",
                            FieldOptions(type="int", min=-5, max=1000))
    rng = np.random.default_rng(11)
    all_cols = []
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        cols = np.sort(rng.choice(SHARD_WIDTH, 150, replace=False)) + base
        f.view("standard", create=True).fragment(
            shard, create=True
        ).bulk_import(np.repeat([1, 2], 75), cols % SHARD_WIDTH)
        for c in cols[::5]:
            g.set_bit(3, int(c))
        for c in cols[:15]:
            fare.set_value(int(c), int(rng.integers(-5, 1000)))
        all_cols.extend(cols.tolist())
    idx.mark_columns_exist(all_cols)
    yield holder
    holder.close()


@pytest.fixture(scope="module")
def executors(holder):
    """One DistExecutor per mesh config, shared across tests so compiled
    programs amortize over the whole module."""
    return {
        cfg: DistExecutor(holder, make_mesh(cfg[0], groups=cfg[1]))
        for cfg in MESH_CONFIGS
    }


@pytest.fixture(scope="module")
def base(holder):
    return Executor(holder)


class TestPaddedShardParity:
    """Satellite: DistExecutor vs single-device results at non-divisible
    shard counts x mesh sizes, all reduce kinds — byte-identical JSON."""

    @pytest.mark.parametrize("cfg", MESH_CONFIGS,
                             ids=[f"{n}dev-g{g or 1}" for n, g in MESH_CONFIGS])
    def test_all_kinds_all_shard_counts(self, cfg, base, executors):
        dist = executors[cfg]
        for k in (1, 5, N_SHARDS):
            shards = list(range(k))
            for pql in KIND_QUERIES:
                (want,) = base.execute("big", pql, shards=shards)
                (got,) = dist.execute("big", pql, shards=shards)
                assert result_to_json(got) == result_to_json(want), (
                    f"mesh={cfg} shards={k} {pql}"
                )

    def test_hier_mesh_shape(self, executors):
        assert mesh_groups(executors[(8, 2)].mesh) == (2, 4)
        assert mesh_groups(executors[(8, 4)].mesh) == (4, 2)
        assert mesh_groups(executors[(2, None)].mesh) is None
        with pytest.raises(ValueError):
            make_mesh(8, groups=3)


class TestWireAccounting:
    def test_lane_dtype_bounds(self):
        assert reduction.lane_dtype_bytes(0) == 1
        assert reduction.lane_dtype_bytes(255) == 1
        assert reduction.lane_dtype_bytes(256) == 2
        assert reduction.lane_dtype_bytes(0xFFFF) == 2
        assert reduction.lane_dtype_bytes(0x10000) == 4

    def test_byte_model(self):
        # count on an 8-device 2x4 mesh, 16 padded slots: the flat ring
        # moves 2*(8-1)*2*4 bytes; the inter-group hop moves
        # G*(G-1)*(lo int32 + hi uint16)
        assert reduction.dense_reduce_bytes(8, 2) == 112
        inter, intra = reduction.hier_reduce_bytes("count", 2, 2, 4, 8)
        assert inter == 2 * 1 * (4 + 2)
        assert intra == 2 * 2 * 3 * 2 * 4

    def test_row_frames_roundtrip(self):
        rng = np.random.default_rng(3)
        host = np.zeros((4, WORDS_PER_SHARD), np.uint32)
        host[1, rng.integers(0, WORDS_PER_SHARD, 300)] = 0x80000001
        host[2, :7] = 0xFFFFFFFF
        frames, nbytes = reduction.encode_row_frames(host)
        assert nbytes < host.nbytes
        back = reduction.decode_row_frames(frames, host.shape)
        np.testing.assert_array_equal(back, host)

    def test_flat_mesh_is_passthrough(self, executors):
        stats = reduction.global_reduce_stats()
        stats.reset()
        executors[(2, None)].execute("big", "Count(Row(f=1))")
        snap = stats.snapshot()
        assert snap["dispatches"] >= 1
        assert snap["hier_dispatches"] == 0
        assert snap["actual_bytes"] == snap["dense_bytes"]
        assert snap["row_gathers"] == 0

    def test_hier_row_topn_4x(self, executors):
        """The bench gate's core assertion, in miniature: Row and TopN
        shapes move >=4x fewer reduction-lane bytes than the dense
        equivalent on the hierarchical mesh."""
        dist = executors[(8, 2)]
        stats = reduction.global_reduce_stats()
        stats.reset()
        dist.execute("big", "Union(Row(f=2), Row(g=3))")
        dist.execute("big", "TopN(f, n=2)")
        snap = stats.snapshot()
        assert snap["row_gathers"] >= 1
        assert snap["row_dense_bytes"] >= 4 * snap["row_actual_bytes"]
        assert snap["hier_dispatches"] >= 1
        assert snap["dense_bytes"] >= 4 * snap["actual_bytes"]

    def test_profile_reduce_bytes(self, executors):
        """reduceBytes rides the PROFILE tree + context totals when the
        hierarchical plane is engaged."""
        prof = cost_mod.QueryProfile("big", "Count(Row(f=1))")
        ctx = cost_mod.new_cost_context("t", "big", profile=prof)
        tok = cost_mod.activate_cost(ctx)
        try:
            executors[(8, 2)].execute("big", "Count(Row(f=1))")
        finally:
            cost_mod.deactivate_cost(tok)
        totals = ctx.totals()
        assert totals["reduceBytes"]["denseEquiv"] > \
            totals["reduceBytes"]["actual"] > 0


class TestQuantizedRanking:
    """Satellite: the EQuARX-style 8-bit candidate-ranking lane
    (`topn-quantized-ranking`). Contracts pinned here:

    * final TopN/GroupBy results are byte-identical to the lossless
      lane on every mesh factorization and shard count (the window
      widening provably covers any rank perturbation, and the window
      is recounted exactly);
    * the numpy property bound — per-row quantization error never
      exceeds the transmitted per-block bound, and the widened window
      always contains the exact top-n;
    * the quantized wire counters flow through ReduceStats (and from
      there to /metrics as dist_reduce_quantized_*).
    """

    # a ranking-heavy field: 64 rows with distinct global counts so the
    # quantized lane has real rank structure to perturb
    @pytest.fixture(scope="class")
    def qholder(self, tmp_path_factory):
        holder = Holder(str(tmp_path_factory.mktemp("meshq") / "data")).open()
        idx = holder.create_index("rank")
        many = idx.create_field("many")
        few = idx.create_field("few")
        cols = []
        for shard in range(N_SHARDS):
            base = shard * SHARD_WIDTH
            c = 0
            for r in range(64):
                # row r gets 2+r bits per shard: every row's global
                # count is distinct, so the ranking has real structure
                # and the widened window can actually shrink
                for _ in range(2 + r):
                    col = base + (c * 97) % SHARD_WIDTH
                    many.set_bit(r, col)
                    cols.append(col)
                    c += 1
            few.set_bit(1, base)
            few.set_bit(2, base + 5)
        idx.mark_columns_exist(cols)
        yield holder
        holder.close()

    @pytest.fixture(scope="class")
    def qbase(self, qholder):
        return Executor(qholder)

    QUANT_QUERIES = [
        "TopN(many, n=3)",
        "TopN(many, n=8)",
        "TopN(many, n=5, threshold=40)",
        "TopN(few, n=2)",
        "GroupBy(Rows(few))",
    ]

    # 1-D flat (lossless pass-through), 2x2, 4x2 — the ISSUE's matrix
    QUANT_CONFIGS = [(2, None), (4, 2), (8, 2)]

    @pytest.mark.parametrize(
        "cfg", QUANT_CONFIGS,
        ids=[f"{n}dev-g{g or 1}" for n, g in QUANT_CONFIGS])
    def test_final_results_byte_identical(self, cfg, qholder, qbase):
        """verify_quantized re-runs the lossless recount in-process and
        raises on ANY divergence, so this also certifies the window."""
        dist = DistExecutor(qholder, make_mesh(cfg[0], groups=cfg[1]),
                            quantized_ranking=True, verify_quantized=True)
        for k in (1, 5, N_SHARDS):  # incl. non-divisible
            shards = list(range(k))
            for pql in self.QUANT_QUERIES:
                (want,) = qbase.execute("rank", pql, shards=shards)
                (got,) = dist.execute("rank", pql, shards=shards)
                assert result_to_json(got) == result_to_json(want), (
                    f"mesh={cfg} shards={k} {pql}"
                )

    def test_error_bound_and_window_coverage_property(self):
        """Pure-numpy property sweep of the device lane's math: the
        per-row reconstruction error never exceeds the transmitted
        per-block bound (so the bound IS a valid window widening), and
        the widened window always contains the exact top-n."""
        rng = np.random.default_rng(5)
        B = reduction.QUANT_BLOCK
        for _ in range(25):
            n_rows = int(rng.integers(1, 700))
            groups = int(rng.integers(1, 5))
            exact_parts = rng.integers(
                0, 1 << int(rng.integers(4, 22)), size=(groups, n_rows))
            nb = reduction.quant_blocks(n_rows)
            padded = np.zeros((groups, nb * B), np.int64)
            padded[:, :n_rows] = exact_parts
            blocks = padded.reshape(groups, nb, B)
            # the device program, re-derived: integer max-scale,
            # deterministic round-to-nearest, 8-bit payload
            s = np.maximum((blocks.max(axis=2) + 254) // 255, 1)
            q = (blocks + (s[:, :, None] >> 1)) // s[:, :, None]
            assert q.max() <= 255
            approx = (q * s[:, :, None]).reshape(
                groups, -1)[:, :n_rows].sum(axis=0)
            err_blocks = np.where(s > 1, (s + 1) >> 1, 0).sum(axis=0)
            err = np.repeat(err_blocks, B)[:n_rows]
            exact = exact_parts.sum(axis=0)
            assert np.all(np.abs(approx - exact) <= err)
            if exact_parts.max() <= 255:
                # sub-byte blocks quantize exactly: zero budget spent
                assert np.all(err == 0) and np.all(approx == exact)
            n = int(rng.integers(1, min(16, n_rows) + 1))
            widx = set(
                np.asarray(
                    reduction.quant_topn_window(approx, err, n)).tolist())
            top = sorted(range(n_rows), key=lambda r: (-exact[r], r))[:n]
            assert set(top) <= widx

    def test_quantized_wire_counters(self, qholder):
        """Production mode (no verify recount): the quantized lane's
        actual inter-group bytes beat the modeled lossless bytes, and
        the window shrinks the exact recount below the candidate set."""
        dist = DistExecutor(qholder, make_mesh(4, groups=2),
                            quantized_ranking=True)
        dist.execute("rank", "TopN(many, n=3)")  # warm the programs
        stats = reduction.global_reduce_stats()
        stats.reset()
        dist.execute("rank", "TopN(many, n=3)")
        snap = stats.snapshot()
        assert snap["quantized_dispatches"] >= 1
        assert 0 < snap["quantized_actual_bytes"] \
            < snap["quantized_lossless_bytes"]
        assert 0 < snap["quantized_window_rows"] \
            < snap["quantized_candidate_rows"]

    def test_pruned_groupby_quantized_levels(self, qholder, qbase,
                                             monkeypatch):
        """Force the prefix-pruning GroupBy strategy: non-final levels
        ride the quantized lane (survival gating on approx+err upper
        bounds never drops a true survivor), the final level is always
        lossless — results byte-identical."""
        import pilosa_tpu.executor.executor as ex_mod

        monkeypatch.setattr(ex_mod, "GROUPBY_DENSE_MAX_GROUPS", 1)
        dist = DistExecutor(qholder, make_mesh(4, groups=2),
                            quantized_ranking=True, verify_quantized=True)
        pql = "GroupBy(Rows(many), Rows(few))"
        (want,) = qbase.execute("rank", pql)
        (got,) = dist.execute("rank", pql)
        assert result_to_json(got) == result_to_json(want)


class TestFallbackGuard:
    """Satellite: when shard_map is the experimental fallback, dispatches
    from executors over DIFFERENT meshes must serialize (the documented
    cross-module all-reduce rendezvous deadlock) instead of relying on a
    comment."""

    def test_concurrent_multi_mesh_serializes(self, holder, executors):
        if dist_mod.SHARD_MAP_NATIVE:
            pytest.skip("native shard_map keys rendezvous by mesh")
        a = executors[(8, 2)]
        b = executors[(4, 2)]
        # warm both programs single-threaded first (compilation under
        # the guard is fine but slow inside threads)
        (want_a,) = a.execute("big", "Count(Row(f=1))")
        (want_b,) = b.execute("big", "Count(Row(f=1))")
        before = dist_mod._guard_serialized_count
        results, errors = {}, []

        def run(name, ex, want):
            try:
                for _ in range(5):
                    (got,) = ex.execute("big", "Count(Row(f=1))")
                    assert got == want
                results[name] = True
            except Exception as e:  # pragma: no cover - failure detail
                errors.append((name, e))

        threads = [threading.Thread(target=run, args=("a", a, want_a)),
                   threading.Thread(target=run, args=("b", b, want_b))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        assert results == {"a": True, "b": True}
        assert dist_mod._guard_serialized_count > before

    def test_single_mesh_unaffected_semantics(self, executors):
        """The guard only engages for multi-mesh: _multi_mesh_live is the
        predicate, and a lone mesh must not trip it."""
        if dist_mod.SHARD_MAP_NATIVE:
            pytest.skip("native shard_map keys rendezvous by mesh")
        mesh = executors[(8, 2)].mesh
        live = {e.mesh for e in dist_mod._LIVE_EXECUTORS}
        # other module-scoped executors exist, so multi-mesh is live now
        assert dist_mod._multi_mesh_live(mesh) == (len(live | {mesh}) > 1)
