"""Hierarchical reduction plane (parallel/reduction.py + the 2-D mesh).

Three contracts, gated here and again (at scale, with records) by the
bench_suite ``mesh`` config:

* bit-exactness — every reduce kind on every mesh factorization returns
  byte-identical results to the single-device Executor, including
  non-divisible shard counts (padded slots);
* the wire model — dense-equivalent vs actual reduction-lane bytes are
  recorded per dispatch, actual is smaller on hierarchical meshes, and
  Row/TopN shapes clear the ≥4x bar the ROADMAP target needs;
* the experimental-fallback guard — concurrent dispatches from
  executors over DIFFERENT meshes serialize instead of deadlocking when
  shard_map comes from jax.experimental.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.result import result_to_json
from pilosa_tpu.parallel import DistExecutor, make_mesh, mesh_groups
from pilosa_tpu.parallel import dist as dist_mod
from pilosa_tpu.parallel import reduction
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.storage import FieldOptions, Holder
from pilosa_tpu.utils import cost as cost_mod

N_SHARDS = 13  # deliberately not a multiple of any mesh size

# mesh sizes 1/2/4/8 including 2-D groups x shards factorizations
MESH_CONFIGS = [(1, None), (2, None), (2, 2), (4, 2), (8, 2), (8, 4)]

# one query per reduce kind: count, row, bsisum, min, max,
# countrows (TopN), groupby + aggregate
KIND_QUERIES = [
    "Count(Row(f=1))",
    "Union(Row(f=2), Row(g=3))",
    "Sum(Row(f=1), field=fare)",
    "Min(field=fare)",
    "Max(field=fare)",
    "TopN(f, n=2)",
    "GroupBy(Rows(f), aggregate=Sum(field=fare))",
]


@pytest.fixture(scope="module")
def holder(tmp_path_factory):
    holder = Holder(str(tmp_path_factory.mktemp("mesh") / "data")).open()
    idx = holder.create_index("big")
    f = idx.create_field("f")
    g = idx.create_field("g")
    fare = idx.create_field("fare",
                            FieldOptions(type="int", min=-5, max=1000))
    rng = np.random.default_rng(11)
    all_cols = []
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        cols = np.sort(rng.choice(SHARD_WIDTH, 150, replace=False)) + base
        f.view("standard", create=True).fragment(
            shard, create=True
        ).bulk_import(np.repeat([1, 2], 75), cols % SHARD_WIDTH)
        for c in cols[::5]:
            g.set_bit(3, int(c))
        for c in cols[:15]:
            fare.set_value(int(c), int(rng.integers(-5, 1000)))
        all_cols.extend(cols.tolist())
    idx.mark_columns_exist(all_cols)
    yield holder
    holder.close()


@pytest.fixture(scope="module")
def executors(holder):
    """One DistExecutor per mesh config, shared across tests so compiled
    programs amortize over the whole module."""
    return {
        cfg: DistExecutor(holder, make_mesh(cfg[0], groups=cfg[1]))
        for cfg in MESH_CONFIGS
    }


@pytest.fixture(scope="module")
def base(holder):
    return Executor(holder)


class TestPaddedShardParity:
    """Satellite: DistExecutor vs single-device results at non-divisible
    shard counts x mesh sizes, all reduce kinds — byte-identical JSON."""

    @pytest.mark.parametrize("cfg", MESH_CONFIGS,
                             ids=[f"{n}dev-g{g or 1}" for n, g in MESH_CONFIGS])
    def test_all_kinds_all_shard_counts(self, cfg, base, executors):
        dist = executors[cfg]
        for k in (1, 5, N_SHARDS):
            shards = list(range(k))
            for pql in KIND_QUERIES:
                (want,) = base.execute("big", pql, shards=shards)
                (got,) = dist.execute("big", pql, shards=shards)
                assert result_to_json(got) == result_to_json(want), (
                    f"mesh={cfg} shards={k} {pql}"
                )

    def test_hier_mesh_shape(self, executors):
        assert mesh_groups(executors[(8, 2)].mesh) == (2, 4)
        assert mesh_groups(executors[(8, 4)].mesh) == (4, 2)
        assert mesh_groups(executors[(2, None)].mesh) is None
        with pytest.raises(ValueError):
            make_mesh(8, groups=3)


class TestWireAccounting:
    def test_lane_dtype_bounds(self):
        assert reduction.lane_dtype_bytes(0) == 1
        assert reduction.lane_dtype_bytes(255) == 1
        assert reduction.lane_dtype_bytes(256) == 2
        assert reduction.lane_dtype_bytes(0xFFFF) == 2
        assert reduction.lane_dtype_bytes(0x10000) == 4

    def test_byte_model(self):
        # count on an 8-device 2x4 mesh, 16 padded slots: the flat ring
        # moves 2*(8-1)*2*4 bytes; the inter-group hop moves
        # G*(G-1)*(lo int32 + hi uint16)
        assert reduction.dense_reduce_bytes(8, 2) == 112
        inter, intra = reduction.hier_reduce_bytes("count", 2, 2, 4, 8)
        assert inter == 2 * 1 * (4 + 2)
        assert intra == 2 * 2 * 3 * 2 * 4

    def test_row_frames_roundtrip(self):
        rng = np.random.default_rng(3)
        host = np.zeros((4, WORDS_PER_SHARD), np.uint32)
        host[1, rng.integers(0, WORDS_PER_SHARD, 300)] = 0x80000001
        host[2, :7] = 0xFFFFFFFF
        frames, nbytes = reduction.encode_row_frames(host)
        assert nbytes < host.nbytes
        back = reduction.decode_row_frames(frames, host.shape)
        np.testing.assert_array_equal(back, host)

    def test_flat_mesh_is_passthrough(self, executors):
        stats = reduction.global_reduce_stats()
        stats.reset()
        executors[(2, None)].execute("big", "Count(Row(f=1))")
        snap = stats.snapshot()
        assert snap["dispatches"] >= 1
        assert snap["hier_dispatches"] == 0
        assert snap["actual_bytes"] == snap["dense_bytes"]
        assert snap["row_gathers"] == 0

    def test_hier_row_topn_4x(self, executors):
        """The bench gate's core assertion, in miniature: Row and TopN
        shapes move >=4x fewer reduction-lane bytes than the dense
        equivalent on the hierarchical mesh."""
        dist = executors[(8, 2)]
        stats = reduction.global_reduce_stats()
        stats.reset()
        dist.execute("big", "Union(Row(f=2), Row(g=3))")
        dist.execute("big", "TopN(f, n=2)")
        snap = stats.snapshot()
        assert snap["row_gathers"] >= 1
        assert snap["row_dense_bytes"] >= 4 * snap["row_actual_bytes"]
        assert snap["hier_dispatches"] >= 1
        assert snap["dense_bytes"] >= 4 * snap["actual_bytes"]

    def test_profile_reduce_bytes(self, executors):
        """reduceBytes rides the PROFILE tree + context totals when the
        hierarchical plane is engaged."""
        prof = cost_mod.QueryProfile("big", "Count(Row(f=1))")
        ctx = cost_mod.new_cost_context("t", "big", profile=prof)
        tok = cost_mod.activate_cost(ctx)
        try:
            executors[(8, 2)].execute("big", "Count(Row(f=1))")
        finally:
            cost_mod.deactivate_cost(tok)
        totals = ctx.totals()
        assert totals["reduceBytes"]["denseEquiv"] > \
            totals["reduceBytes"]["actual"] > 0


class TestFallbackGuard:
    """Satellite: when shard_map is the experimental fallback, dispatches
    from executors over DIFFERENT meshes must serialize (the documented
    cross-module all-reduce rendezvous deadlock) instead of relying on a
    comment."""

    def test_concurrent_multi_mesh_serializes(self, holder, executors):
        if dist_mod.SHARD_MAP_NATIVE:
            pytest.skip("native shard_map keys rendezvous by mesh")
        a = executors[(8, 2)]
        b = executors[(4, 2)]
        # warm both programs single-threaded first (compilation under
        # the guard is fine but slow inside threads)
        (want_a,) = a.execute("big", "Count(Row(f=1))")
        (want_b,) = b.execute("big", "Count(Row(f=1))")
        before = dist_mod._guard_serialized_count
        results, errors = {}, []

        def run(name, ex, want):
            try:
                for _ in range(5):
                    (got,) = ex.execute("big", "Count(Row(f=1))")
                    assert got == want
                results[name] = True
            except Exception as e:  # pragma: no cover - failure detail
                errors.append((name, e))

        threads = [threading.Thread(target=run, args=("a", a, want_a)),
                   threading.Thread(target=run, args=("b", b, want_b))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        assert results == {"a": True, "b": True}
        assert dist_mod._guard_serialized_count > before

    def test_single_mesh_unaffected_semantics(self, executors):
        """The guard only engages for multi-mesh: _multi_mesh_live is the
        predicate, and a lone mesh must not trip it."""
        if dist_mod.SHARD_MAP_NATIVE:
            pytest.skip("native shard_map keys rendezvous by mesh")
        mesh = executors[(8, 2)].mesh
        live = {e.mesh for e in dist_mod._LIVE_EXECUTORS}
        # other module-scoped executors exist, so multi-mesh is live now
        assert dist_mod._multi_mesh_live(mesh) == (len(live | {mesh}) > 1)
