"""Two-tier HBM residency: demote-compress on eviction, scatter-promote
on hit (storage/residency.py; SURVEY.md §7.3 hard part #1)."""

import numpy as np
import pytest

from pilosa_tpu.shardwidth import WORDS_PER_SHARD
from pilosa_tpu.storage.residency import (
    COMPRESS_BLOCK_WORDS,
    ROW_BYTES,
    DeviceRowCache,
)


def sparse_row(rng, n_blocks_set):
    """Dense uint32[WORDS_PER_SHARD] with data in n_blocks_set blocks."""
    row = np.zeros(WORDS_PER_SHARD, np.uint32)
    total = WORDS_PER_SHARD // COMPRESS_BLOCK_WORDS
    for b in rng.choice(total, n_blocks_set, replace=False):
        lo = b * COMPRESS_BLOCK_WORDS
        row[lo : lo + COMPRESS_BLOCK_WORDS] = rng.integers(
            1, 1 << 32, COMPRESS_BLOCK_WORDS, dtype=np.uint32
        )
    return row


class CountingDecoder:
    def __init__(self, host):
        self.host = host
        self.calls = 0

    def __call__(self):
        self.calls += 1
        return self.host


def test_demote_compress_promote_roundtrip():
    rng = np.random.default_rng(7)
    # budget holds one 128 KiB row; the second insert forces demotion
    cache = DeviceRowCache(budget_bytes=200 << 10)
    a = CountingDecoder(sparse_row(rng, 3))
    b = CountingDecoder(sparse_row(rng, 2))

    cache.get_row(("a",), a)
    cache.get_row(("b",), b)  # evicts a from dense -> compressed tier
    assert cache.compressions == 1
    assert cache.compressed_bytes < ROW_BYTES // 4  # 3/32 blocks + idx

    got = np.asarray(cache.get_row(("a",), a))  # promote, no re-decode
    assert a.calls == 1
    assert cache.decompressions == 1
    np.testing.assert_array_equal(got, a.host)
    # and b was in turn demoted; its round trip is exact too
    got_b = np.asarray(cache.get_row(("b",), b))
    assert b.calls == 1
    np.testing.assert_array_equal(got_b, b.host)


def test_dense_rows_drop_instead_of_compress():
    rng = np.random.default_rng(8)
    cache = DeviceRowCache(budget_bytes=200 << 10)
    full = CountingDecoder(
        rng.integers(1, 1 << 32, WORDS_PER_SHARD, dtype=np.uint32)
    )
    other = CountingDecoder(sparse_row(rng, 1))
    cache.get_row(("full",), full)
    cache.get_row(("other",), other)
    assert cache.compressions == 0  # >50% occupancy: dropped, not kept
    assert cache.evictions == 1
    cache.get_row(("full",), full)
    assert full.calls == 2  # re-decoded from host


def test_all_zero_row_roundtrip():
    cache = DeviceRowCache(budget_bytes=200 << 10)
    zero = CountingDecoder(np.zeros(WORDS_PER_SHARD, np.uint32))
    filler = CountingDecoder(np.ones(WORDS_PER_SHARD, np.uint32))
    cache.get_row(("z",), zero)
    cache.get_row(("f",), filler)
    assert cache.compressions == 1
    got = np.asarray(cache.get_row(("z",), zero))
    assert zero.calls == 1
    assert not got.any()


def test_invalidate_hits_both_tiers():
    rng = np.random.default_rng(9)
    cache = DeviceRowCache(budget_bytes=200 << 10)
    a = CountingDecoder(sparse_row(rng, 2))
    b = CountingDecoder(sparse_row(rng, 2))
    cache.get_row(("frag", 1, "a"), a)
    cache.get_row(("frag", 1, "b"), b)  # a now compressed
    cache.invalidate_fragment(("frag", 1))
    assert len(cache) == 0 and cache.bytes_used == 0
    cache.get_row(("frag", 1, "a"), a)
    assert a.calls == 2


def test_compressed_tier_evicts_under_total_budget():
    rng = np.random.default_rng(10)
    # tiny budget: dense holds one row; compressed tier must stay under
    # total - so repeated inserts eventually drop the oldest compressed
    cache = DeviceRowCache(budget_bytes=160 << 10)
    decoders = [CountingDecoder(sparse_row(rng, 14)) for _ in range(16)]
    for i, d in enumerate(decoders):
        cache.get_row((i,), d)
    assert cache.bytes_used <= cache.budget_bytes + ROW_BYTES  # 1 dense floor
    assert cache.evictions > 0  # compressed tier did overflow


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_randomized_roundtrip_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    cache = DeviceRowCache(budget_bytes=200 << 10)
    hosts = {}
    for i in range(6):
        nb = int(rng.integers(0, 16))
        hosts[i] = sparse_row(rng, nb)
        cache.get_row((i,), CountingDecoder(hosts[i]))
    for i in rng.permutation(6):
        got = np.asarray(cache.get_row((int(i),), CountingDecoder(hosts[int(i)])))
        np.testing.assert_array_equal(got, hosts[int(i)])


def test_stacked_leaf_shapes_compress():
    """Multi-dim uint32 arrays (stacked shard leaves, BSI planes) take the
    same path."""
    rng = np.random.default_rng(11)
    cache = DeviceRowCache(budget_bytes=500 << 10)
    stacked = np.stack([sparse_row(rng, 2) for _ in range(2)])
    planes = np.zeros((2, 3, WORDS_PER_SHARD), np.uint32)
    planes[0, 1, :COMPRESS_BLOCK_WORDS] = 5
    big = CountingDecoder(
        rng.integers(1, 1 << 32, (2, WORDS_PER_SHARD), dtype=np.uint32)
    )
    cache.get_row(("s",), CountingDecoder(stacked))
    cache.get_row(("p",), CountingDecoder(planes))
    cache.get_row(("big",), big)  # forces demotions
    assert cache.compressions >= 1
    np.testing.assert_array_equal(
        np.asarray(cache.get_row(("s",), CountingDecoder(stacked))), stacked
    )
    np.testing.assert_array_equal(
        np.asarray(cache.get_row(("p",), CountingDecoder(planes))), planes
    )


def test_working_set_within_budget_stays_dense():
    """No demotion while everything fits: full-budget dense residency
    (regression guard: the two-tier split must not shrink the hot tier)."""
    rng = np.random.default_rng(12)
    cache = DeviceRowCache(budget_bytes=600 << 10)  # 4 rows fit
    decs = [CountingDecoder(sparse_row(rng, 2)) for _ in range(4)]
    for i, d in enumerate(decs):
        cache.get_row((i,), d)
    for _ in range(3):
        for i, d in enumerate(decs):
            cache.get_row((i,), d)
    assert cache.compressions == 0 and cache.evictions == 0
    assert all(d.calls == 1 for d in decs)


def test_apply_write_patches_dense_and_spares_unrelated():
    """A write routes to exactly the tagged+affected entries: the affected
    dense entry is patched in place (no eviction, no re-decode); entries
    under other tags or probed-unaffected stay untouched."""
    from pilosa_tpu.storage.residency import WriteEvent

    rng = np.random.default_rng(13)
    cache = DeviceRowCache(budget_bytes=4 << 20)
    affected = CountingDecoder(sparse_row(rng, 2))
    unrelated = CountingDecoder(sparse_row(rng, 2))
    cache.get_row(("stack", "i", "f", 1), affected)
    cache.get_row(("stack", "i", "g", 1), unrelated)

    import jax.numpy as jnp

    probed = []

    def probe(ev):
        probed.append(ev.row)
        if ev.row != 1:
            return None
        return lambda arr: arr | jnp.uint32(1)

    cache.register_updater(("stack", "i", "f", 1), ("", "i", "f"), probe)
    cache.apply_write(WriteEvent("i", "f", "standard", 0, 1))
    assert probed == [1] and cache.updates == 1
    assert len(cache) == 2 and cache.misses == 2  # nothing evicted
    got = np.asarray(cache.get_row(("stack", "i", "f", 1), affected))
    np.testing.assert_array_equal(got, affected.host | np.uint32(1))
    assert affected.calls == 1  # patched, never re-decoded
    # unaffected row: probe returns None, entry untouched
    cache.apply_write(WriteEvent("i", "f", "standard", 0, 7))
    assert cache.updates == 1
    # other tag never probed
    cache.apply_write(WriteEvent("i", "g", "standard", 0, 1))
    assert probed == [1, 7]


def test_apply_write_invalidates_compressed_copies():
    """An affected entry demoted to the compressed tier is invalidated
    (not patched); unaffected compressed entries survive the write."""
    from pilosa_tpu.storage.residency import WriteEvent

    rng = np.random.default_rng(14)
    cache = DeviceRowCache(budget_bytes=200 << 10)  # one dense row fits
    a = CountingDecoder(sparse_row(rng, 2))
    b = CountingDecoder(sparse_row(rng, 2))
    cache.get_row(("stack", "i", "f", 1), a)

    def probe_hit(ev):
        return (lambda arr: arr) if ev.row == 1 else None

    cache.register_updater(("stack", "i", "f", 1), ("", "i", "f"), probe_hit)
    cache.get_row(("stack", "i", "f", 2), b)  # demotes a to compressed
    assert cache.compressions == 1
    cache.apply_write(WriteEvent("i", "f", "standard", 0, 1))
    assert ("stack", "i", "f", 1) not in cache._compressed  # invalidated
    assert ("stack", "i", "f", 2) in cache._rows  # dense+unaffected: kept


def test_updaters_dropped_with_entries():
    from pilosa_tpu.storage.residency import WriteEvent

    rng = np.random.default_rng(15)
    cache = DeviceRowCache(budget_bytes=4 << 20)
    cache.get_row(("k",), CountingDecoder(sparse_row(rng, 2)))
    cache.register_updater(("k",), ("", "i", "f"), lambda ev: None)
    assert ("", "i", "f") in cache._tag_index
    cache.invalidate(("k",))
    assert not cache._tag_index and not cache._updaters
    # registering for a non-resident key is a no-op
    cache.register_updater(("gone",), ("", "i", "f"), lambda ev: None)
    assert not cache._updaters
    cache.apply_write(WriteEvent("i", "f", "standard", 0, 1))  # no crash


def test_touch_refreshes_lru_position():
    """touch() keeps served-from-memo leaves from looking LRU-cold:
    under pressure the UNtouched entry must be the eviction victim."""
    rng = np.random.default_rng(11)
    cache = DeviceRowCache(budget_bytes=300 << 10)  # two rows fit
    hot = CountingDecoder(sparse_row(rng, 20))
    cold = CountingDecoder(sparse_row(rng, 20))
    cache.get_row(("hot",), hot)
    cache.get_row(("cold",), cold)  # insertion order: hot is LRU-oldest
    cache.touch([("hot",), ("missing",)])  # missing keys are ignored
    gen0 = cache.generation
    cache.get_row(("new",), CountingDecoder(sparse_row(rng, 20)))  # over budget
    assert cache.generation > gen0  # eviction bumped
    cache.get_row(("hot",), hot)
    assert hot.calls == 1  # survived: touched after cold
    cache.get_row(("cold",), cold)
    assert cold.calls == 2  # evicted: it was the LRU-coldest


def test_generation_listener_weakly_held():
    """Listener mechanics: fires on a bump, dead registrants dropped,
    remove_generation_listener unregisters."""
    calls = []

    class L:
        def cb(self):
            calls.append(1)

    c1 = DeviceRowCache(budget_bytes=1 << 20)
    listener = L()
    c1.add_generation_listener(listener.cb)
    c1.get_row(("x",), CountingDecoder(sparse_row(np.random.default_rng(1), 20)))
    c1.invalidate(("x",))
    assert calls == [1]  # bump fired the listener
    c1.remove_generation_listener(listener.cb)
    c1.get_row(("x",), CountingDecoder(sparse_row(np.random.default_rng(1), 20)))
    c1.invalidate(("x",))
    assert calls == [1]  # removed: no further calls
    keeper = L()
    c1.add_generation_listener(keeper.cb)
    listener2 = L()
    c1.add_generation_listener(listener2.cb)
    del listener2
    c1.get_row(("x",), CountingDecoder(sparse_row(np.random.default_rng(1), 20)))
    c1.invalidate(("x",))
    assert calls == [1, 1]  # weakly held: dead listener dropped, live kept


def test_executor_memo_rehomes_on_cache_swap(tmp_path):
    """Executor re-home integration (executor.py _eval_operands): after
    set_global_row_cache swaps the live cache, (a) the memo is cleared
    and rebuilt against the NEW cache, (b) the listener moves — bumps on
    the OLD cache no longer clear the live memo, (c) a swap-back does
    not stack duplicate registrations."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.storage import residency as res_mod

    holder = Holder(str(tmp_path / "data")).open()
    old = res_mod.global_row_cache()
    try:
        f = holder.create_index("i").create_field("f")
        f.set_bit(1, 3)
        f.set_bit(1, 99)
        ex = Executor(holder)
        c1 = DeviceRowCache(budget_bytes=8 << 20)
        res_mod.set_global_row_cache(c1)
        assert ex.execute("i", "Count(Row(f=1))") == [2]
        assert ex.execute("i", "Count(Row(f=1))") == [2]  # memo hit path
        assert ex._listened_cache is c1 and ex._operand_memo

        c2 = DeviceRowCache(budget_bytes=8 << 20)
        res_mod.set_global_row_cache(c2)
        assert ex.execute("i", "Count(Row(f=1))") == [2]
        assert ex._listened_cache is c2 and ex._operand_memo
        # (b) old-cache bumps must NOT clear the memo tracking c2
        c1.get_row(("x",), CountingDecoder(sparse_row(np.random.default_rng(1), 20)))
        c1.invalidate(("x",))
        assert ex._operand_memo, "stale cache bump cleared the live memo"
        # ...while a bump on the LIVE cache still clears it eagerly
        c2.get_row(("x",), CountingDecoder(sparse_row(np.random.default_rng(1), 20)))
        c2.invalidate(("x",))
        assert not ex._operand_memo

        # (c) swap-back: exactly one live registration per cache
        res_mod.set_global_row_cache(c1)
        assert ex.execute("i", "Count(Row(f=1))") == [2]
        assert ex._listened_cache is c1
        alive = [r for r in c1._gen_listeners if r() is not None]
        assert len(alive) == 1
        assert not [r for r in c2._gen_listeners if r() is not None]
    finally:
        res_mod.set_global_row_cache(old)
        holder.close()
