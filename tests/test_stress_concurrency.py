"""Mixed-workload concurrency stress (SURVEY.md §4: the reference runs
its suite under -race; CPython's races surface as torn state, dropped
patches, or RuntimeErrors instead of sanitizer reports).

One holder takes concurrent writers + queries + anti-entropy + snapshots
for a couple of seconds; every thread's exception fails the test, and
the final state must exactly match the write oracle on both replicas.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.server import Server, ServerConfig
from pilosa_tpu.shardwidth import SHARD_WIDTH

N_WRITERS = 3
BATCHES_PER_WRITER = 12
BITS_PER_BATCH = 200


def req(method, url, body=None, ct="application/json"):
    data = body if isinstance(body, (bytes, type(None))) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        r.add_header("Content-Type", ct)
    with urllib.request.urlopen(r, timeout=60) as resp:
        return json.loads(resp.read() or b"{}")


@pytest.fixture
def cluster2(tmp_path):
    servers = []
    for i in range(2):
        seeds = [f"http://localhost:{servers[0].port}"] if servers else []
        servers.append(Server(ServerConfig(
            data_dir=str(tmp_path / f"node{i}"), port=0, name=f"n{i}",
            replica_n=2, seeds=seeds, anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
        )).open())
    yield servers
    for s in servers:
        s.close()


def test_writers_queries_antientropy_snapshot(cluster2):
    servers = cluster2
    base = [f"http://localhost:{s.port}" for s in servers]
    req("POST", f"{base[0]}/index/i", {})
    req("POST", f"{base[0]}/index/i/field/f", {})
    req("POST", f"{base[0]}/index/i/field/v",
        {"options": {"type": "int", "min": 0, "max": 100000}})
    req("POST", f"{base[0]}/index/i/field/m", {"options": {"type": "mutex"}})

    errors: list[BaseException] = []
    stop = threading.Event()

    def guard(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - collect everything
                errors.append(e)
                stop.set()
        return run

    # disjoint column ranges per (writer, batch): the oracle is exact
    def writer(w: int):
        def go():
            rng = np.random.default_rng(w)
            for b in range(BATCHES_PER_WRITER):
                lo = (w * BATCHES_PER_WRITER + b) * BITS_PER_BATCH
                cols = [int(c) for c in
                        rng.permutation(np.arange(lo, lo + BITS_PER_BATCH))]
                # spread across two shards to hit two fragments
                cols = [c if c % 2 else c + SHARD_WIDTH for c in cols]
                req("POST", f"{base[b % 2]}/index/i/field/f/import",
                    {"rows": [1] * len(cols), "columns": cols})
                if stop.is_set():
                    return
        return go

    # batched BSI imports racing everything else: writer-disjoint column
    # ranges at a fixed offset; value = writer*100+batch (exact oracle)
    BSI_BASE = 4 * SHARD_WIDTH

    def bsi_writer(w: int):
        def go():
            for b in range(BATCHES_PER_WRITER):
                lo = BSI_BASE + (w * BATCHES_PER_WRITER + b) * 50
                cols = list(range(lo, lo + 50))
                req("POST", f"{base[b % 2]}/index/i/field/v/import-value",
                    {"columns": cols, "values": [w * 100 + b] * 50})
                if stop.is_set():
                    return
        return go

    # mutex imports: each writer owns a column range and re-imports it
    # under successive rows; the LAST batch's row must win everywhere
    MUTEX_BASE = 6 * SHARD_WIDTH

    def mutex_writer(w: int):
        def go():
            cols = list(range(MUTEX_BASE + w * 100, MUTEX_BASE + w * 100 + 100))
            for b in range(BATCHES_PER_WRITER):
                req("POST", f"{base[b % 2]}/index/i/field/m/import",
                    {"rows": [b % 3] * len(cols), "columns": cols})
                if stop.is_set():
                    return
        return go

    def querier():
        last = 0
        while not stop.is_set():
            out = req("POST", f"{base[0]}/index/i/query",
                      b"Count(Row(f=1))", "text/plain")
            n = out["results"][0]
            # bits are only added: the count must never go backwards
            assert n >= last, (n, last)
            last = n
            req("POST", f"{base[1]}/index/i/query", b"TopN(f, n=4)",
                "text/plain")

    def pipelined_submitter():
        """Micro-batched submit streams racing the writers: leaves are
        captured at enqueue and writes only add bits, so resolved counts
        must be non-decreasing in submit order; TopN rides the same
        pipeline (countrows micro-batch + candidate-matrix patching)."""
        ex = servers[0].api.executor.local
        last = 0
        while not stop.is_set():
            defs = [ex.submit("i", "Count(Row(f=1))")[0] for _ in range(8)]
            topn = ex.submit("i", "TopN(f, n=4)")[0]
            for d in defs:
                n = d.result()
                assert n >= last, (n, last)
                last = n
            pairs = topn.result()
            assert all(p.count > 0 for p in pairs)

    def anti_entropy():
        while not stop.is_set():
            for s in servers:
                s.api.cluster.sync_holder()

    def snapshotter():
        while not stop.is_set():
            for s in servers:
                idx = s.holder.index("i")
                field = idx.field("f") if idx else None
                view = field.view("standard") if field else None
                if view is None:
                    continue
                for frag in list(view.fragments.values()):
                    frag.snapshot()

    writers = [threading.Thread(target=guard(writer(w))) for w in range(N_WRITERS)]
    writers += [threading.Thread(target=guard(bsi_writer(w)))
                for w in range(2)]
    writers += [threading.Thread(target=guard(mutex_writer(w)))
                for w in range(2)]
    aux = [threading.Thread(target=guard(fn), daemon=True)
           for fn in (querier, pipelined_submitter, anti_entropy,
                      snapshotter)]
    for t in writers + aux:
        t.start()
    for t in writers:
        t.join(timeout=120)
        assert not t.is_alive()
    stop.set()
    for t in aux:
        t.join(timeout=30)
    assert not errors, errors[0]

    # exact final state on both replicas (one quiescent sync first)
    for s in servers:
        s.api.cluster.sync_holder()
    want = N_WRITERS * BATCHES_PER_WRITER * BITS_PER_BATCH
    bsi_want_cols = 2 * BATCHES_PER_WRITER * 50
    bsi_want_sum = sum(
        (w * 100 + b) * 50
        for w in range(2) for b in range(BATCHES_PER_WRITER)
    )
    final_row = (BATCHES_PER_WRITER - 1) % 3
    for b in base:
        out = req("POST", f"{b}/index/i/query", b"Count(Row(f=1))",
                  "text/plain")
        assert out["results"] == [want]
        out = req("POST", f"{b}/index/i/query", b'Sum(field="v")',
                  "text/plain")
        assert out["results"][0] == {
            "value": bsi_want_sum, "count": bsi_want_cols,
        }
        # single-value invariant held on every replica: each mutex
        # column sits in exactly its LAST imported row
        out = req("POST", f"{b}/index/i/query",
                  f"Count(Row(m={final_row}))".encode(), "text/plain")
        assert out["results"] == [200]
        for other in range(3):
            if other == final_row:
                continue
            out = req("POST", f"{b}/index/i/query",
                      f"Count(Row(m={other}))".encode(), "text/plain")
            assert out["results"] == [0], other
