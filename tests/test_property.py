"""Randomized full-stack property tests: a random write workload followed
by the whole read-query surface, checked against a pure-python oracle and
cross-checked between the local and mesh executors.

This is the end-to-end analog of the reference's oracle-checked randomized
container tests (roaring_test.go quick-check style — SURVEY.md §4): the
writes go through the real storage tree (fragments, op logs, caches), the
queries through the real compiled kernels, and nothing is mocked.
"""

import functools
import operator

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.result import ValCount
from pilosa_tpu.parallel.dist import DistExecutor
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import FieldOptions, Holder

N_SHARDS = 5
COL_SPACE = N_SHARDS * SHARD_WIDTH
ROWS = [1, 2, 3, 7]
MUTEX_ROWS = [0, 1, 2]
INT_MIN, INT_MAX = -50, 1000
# time-quantum workload: a small pool of timestamps spanning Y/M/D/H
# boundaries so the YMDH view cover is exercised on every granularity
TIMESTAMPS = [
    "2019-01-15T00:00", "2019-01-15T07:00", "2019-03-02T00:00",
    "2019-12-31T23:00", "2020-01-01T00:00", "2021-06-30T12:00",
]


class Oracle:
    """Pure-python model: set field row -> cols; mutex/bool col -> row;
    time (row, ts) -> cols; int col -> value; row/col attrs; existence."""

    def __init__(self):
        self.sets: dict[int, set[int]] = {r: set() for r in ROWS}
        self.values: dict[int, int] = {}
        self.exists: set[int] = set()
        self.mutex: dict[int, int] = {}          # col -> row
        self.bools: dict[int, int] = {}          # col -> 0/1
        self.time: dict[tuple, set] = {}         # (row, ts) -> cols
        self.row_attrs: dict[int, dict] = {}     # f row -> attrs
        self.col_attrs: dict[int, dict] = {}     # col -> attrs

    def set_bit(self, row, col):
        self.sets[row].add(col)
        self.exists.add(col)

    def clear_bit(self, row, col):
        self.sets[row].discard(col)

    def set_value(self, col, val):
        self.values[col] = val
        self.exists.add(col)

    def set_mutex(self, row, col):
        self.mutex[col] = row
        self.exists.add(col)

    def set_bool(self, row, col):
        self.bools[col] = row
        self.exists.add(col)

    def set_time(self, row, col, ts):
        self.time.setdefault((row, ts), set()).add(col)
        self.exists.add(col)

    def mutex_row(self, row):
        return {c for c, r in self.mutex.items() if r == row}

    def bool_row(self, row):
        return {c for c, r in self.bools.items() if r == row}

    def time_row(self, row, lo, hi):
        """Columns of ``row`` with any event timestamp in [lo, hi) —
        the executor's view cover treats ``to=`` as exclusive."""
        return {
            c for (r, ts), cols in self.time.items() if r == row
            for c in cols if lo <= ts < hi
        }


def random_workload(rng, ex, index, oracle, n_ops=120):
    """Random writes through PQL over every field type: set bits, mutex
    and bool single-value semantics, time-quantum events, BSI values,
    row/column attrs, and row-wide Store/ClearRow."""
    for _ in range(n_ops):
        col = int(rng.integers(0, COL_SPACE))
        op = rng.random()
        if op < 0.40:
            row = int(rng.choice(ROWS))
            ex.execute(index, f"Set({col}, f={row})")
            oracle.set_bit(row, col)
        elif op < 0.55:
            row = int(rng.choice(ROWS))
            ex.execute(index, f"Clear({col}, f={row})")
            oracle.clear_bit(row, col)
        elif op < 0.68:
            val = int(rng.integers(INT_MIN, INT_MAX + 1))
            ex.execute(index, f"Set({col}, v={val})")
            oracle.set_value(col, val)
        elif op < 0.76:
            row = int(rng.choice(MUTEX_ROWS))
            ex.execute(index, f"Set({col}, m={row})")
            oracle.set_mutex(row, col)
        elif op < 0.82:
            row = int(rng.integers(0, 2))
            ex.execute(index, f"Set({col}, b={'true' if row else 'false'})")
            oracle.set_bool(row, col)
        elif op < 0.90:
            row = int(rng.choice(ROWS))
            ts = TIMESTAMPS[int(rng.integers(0, len(TIMESTAMPS)))]
            ex.execute(index, f"Set({col}, t={row}, timestamp='{ts}')")
            oracle.set_time(row, col, ts)
        elif op < 0.94:
            row = int(rng.choice(ROWS))
            v = int(rng.integers(0, 100))
            ex.execute(index, f'SetRowAttrs(f, {row}, rank={v}, hot=true)')
            oracle.row_attrs.setdefault(row, {}).update(
                {"rank": v, "hot": True}
            )
        elif op < 0.97:
            v = int(rng.integers(0, 100))
            ex.execute(index, f'SetColumnAttrs({col}, score={v})')
            oracle.col_attrs.setdefault(col, {}).update({"score": v})
        elif op < 0.985:
            src, dst = (int(r) for r in rng.choice(ROWS, 2, replace=False))
            ex.execute(index, f"Store(Row(f={src}), f={dst})")
            oracle.sets[dst] = set(oracle.sets[src])
        else:
            row = int(rng.choice(ROWS))
            ex.execute(index, f"ClearRow(f={row})")
            oracle.sets[row] = set()


def random_expr(rng, depth=0):
    """Random bitmap expression tree -> (pql, eval(oracle) -> set)."""
    r = rng.random()
    if depth >= 2 or r < 0.35:
        row = int(rng.choice(ROWS))
        return f"Row(f={row})", lambda o: set(o.sets[row])
    op = rng.choice(["Union", "Intersect", "Difference", "Xor", "Not"])
    if op == "Not":
        pql, ev = random_expr(rng, depth + 1)
        return f"Not({pql})", lambda o: o.exists - ev(o)
    n = 2 if op in ("Difference", "Xor") else int(rng.integers(2, 4))
    subs = [random_expr(rng, depth + 1) for _ in range(n)]
    pql = f"{op}({', '.join(p for p, _ in subs)})"

    def ev(o, op=op, subs=subs):
        vals = [e(o) for _, e in subs]
        if op == "Union":
            return set().union(*vals)
        if op == "Intersect":
            return functools.reduce(operator.and_, vals)
        if op == "Difference":
            return vals[0] - vals[1]
        return vals[0] ^ vals[1]

    return pql, ev


def make_env(tmp_path, name):
    holder = Holder(str(tmp_path / name)).open()
    idx = holder.create_index("i", track_existence=True)
    idx.create_field("f")
    idx.create_field("v", FieldOptions(type="int", min=INT_MIN, max=INT_MAX))
    idx.create_field("m", FieldOptions(type="mutex"))
    idx.create_field("b", FieldOptions(type="bool"))
    idx.create_field("t", FieldOptions(type="time", time_quantum="YMDH"))
    return holder


def check_field_types(rng, ex, oracle):
    """Field-type invariants vs the oracle: mutex/bool single-value
    rows, time-quantum range cover, row/column attrs."""
    for row in MUTEX_ROWS:
        (res,) = ex.execute("i", f"Row(m={row})")
        assert set(res.columns().tolist()) == oracle.mutex_row(row), row
    for word, row in [("true", 1), ("false", 0)]:
        (res,) = ex.execute("i", f"Row(b={word})")
        assert set(res.columns().tolist()) == oracle.bool_row(row), word
    # time ranges at every granularity the quantum generates (plus a
    # random window); standard view must hold the union of all events
    windows = [
        ("2019-01-01T00:00", "2019-12-31T23:00"),
        ("2019-01-15T00:00", "2019-01-15T07:00"),
        ("2019-03-01T00:00", "2020-06-01T00:00"),
        tuple(sorted(
            TIMESTAMPS[i] for i in rng.choice(len(TIMESTAMPS), 2,
                                              replace=False)
        )),
    ]
    for row in ROWS:
        for lo, hi in windows:
            (res,) = ex.execute(
                "i", f"Row(t={row}, from='{lo}', to='{hi}')"
            )
            assert set(res.columns().tolist()) == oracle.time_row(
                row, lo, hi
            ), (row, lo, hi)
        (res,) = ex.execute("i", f"Row(t={row})")
        want = {
            c for (r, _), cols in oracle.time.items() if r == row
            for c in cols
        }
        assert set(res.columns().tolist()) == want, row
    # attrs ride the row result; column attrs read back per column
    for row, attrs in oracle.row_attrs.items():
        (res,) = ex.execute("i", f"Row(f={row})")
        assert res.attrs == attrs, row
    idx = ex.holder.index("i")
    for col, attrs in oracle.col_attrs.items():
        assert idx.column_attrs.attrs(col) == attrs, col


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_workload_vs_oracle(tmp_path, seed):
    rng = np.random.default_rng(seed)
    holder = make_env(tmp_path, "d")
    ex = Executor(holder)
    oracle = Oracle()
    try:
        for round_ in range(3):
            random_workload(rng, ex, "i", oracle, n_ops=150)
            check_field_types(rng, ex, oracle)

            # bitmap expressions + counts
            for _ in range(6):
                pql, ev = random_expr(rng)
                want = ev(oracle)
                (res,) = ex.execute("i", pql)
                assert set(res.columns().tolist()) == want, pql
                (n,) = ex.execute("i", f"Count({pql})")
                assert n == len(want), pql

            # existence
            (res,) = ex.execute("i", "All()")
            assert set(res.columns().tolist()) == oracle.exists

            # BSI: every compare op + aggregates against the value map
            vals = oracle.values
            for op_pql, pred in [
                (">", lambda v, k: v > k), ("<", lambda v, k: v < k),
                (">=", lambda v, k: v >= k), ("<=", lambda v, k: v <= k),
                ("==", lambda v, k: v == k), ("!=", lambda v, k: v != k),
            ]:
                k = int(rng.integers(INT_MIN, INT_MAX + 1))
                (res,) = ex.execute("i", f"Range(v {op_pql} {k})")
                want = {c for c, v in vals.items() if pred(v, k)}
                assert set(res.columns().tolist()) == want, (op_pql, k)
            if vals:
                (s,) = ex.execute("i", 'Sum(field="v")')
                assert s == ValCount(sum(vals.values()), len(vals))
                (mn,) = ex.execute("i", 'Min(field="v")')
                assert mn.value == min(vals.values())
                (mx,) = ex.execute("i", 'Max(field="v")')
                assert mx.value == max(vals.values())

            # TopN (cache is large enough to be exact) and Rows
            (pairs,) = ex.execute("i", "TopN(f)")
            want_pairs = sorted(
                ((r, len(c)) for r, c in oracle.sets.items() if c),
                key=lambda t: (-t[1], t[0]),
            )
            assert [(p.id, p.count) for p in pairs] == want_pairs
            (rows,) = ex.execute("i", "Rows(f)")
            assert rows == sorted(r for r, c in oracle.sets.items() if c)

            # GroupBy counts per row
            (groups,) = ex.execute("i", "GroupBy(Rows(f))")
            got = {g.group[0]["rowID"]: g.count for g in groups}
            assert got == {r: len(c) for r, c in oracle.sets.items() if c}

            # Options(shards=): a random shard subset restricts the
            # evaluated universe exactly
            subset = sorted(
                int(s) for s in rng.choice(N_SHARDS, 2, replace=False)
            )
            pql, ev = random_expr(rng)
            want_cols = {
                c for c in ev(oracle) if c // SHARD_WIDTH in subset
            }
            (n,) = ex.execute(
                "i", f"Options(Count({pql}), shards={subset})"
            )
            assert n == len(want_cols), (pql, subset)

            # round-4 surface: TopN(threshold=) and GroupBy(having=)
            # against the same oracle, with a random floor
            thr = int(rng.integers(1, 40))
            (pairs,) = ex.execute("i", f"TopN(f, threshold={thr})")
            assert [(p.id, p.count) for p in pairs] == [
                (r, n) for r, n in want_pairs if n >= thr
            ]
            (groups,) = ex.execute(
                "i", f"GroupBy(Rows(f), having=Condition(count >= {thr}))"
            )
            got = {g.group[0]["rowID"]: g.count for g in groups}
            assert got == {r: len(c) for r, c in oracle.sets.items()
                           if len(c) >= thr}

            # pipelined submit() answers exactly as execute() (quiescent
            # holder: leaves captured at enqueue match)
            from pilosa_tpu.executor.result import result_to_json

            pqls = [f"Count({random_expr(rng)[0]})" for _ in range(6)]
            pqls += ["TopN(f)", "GroupBy(Rows(f))", 'Sum(field="v")']
            defs = [ex.submit("i", p)[0] for p in pqls]
            for p, d in zip(pqls, defs):
                want_r = result_to_json(ex.execute("i", p)[0])
                assert result_to_json(d.result()) == want_r, p
    finally:
        holder.close()


@pytest.mark.parametrize("seed", [99])
def test_cluster_randomized_with_membership_churn(tmp_path, seed):
    """Randomized workload against a REPLICATED cluster with membership
    churn in the middle: writes through alternating nodes, a third node
    joins mid-workload (async resize), a node leaves gracefully after —
    and at every stage the read surface matches the oracle from every
    live node (SURVEY §4's quick-check-vs-oracle lesson applied to the
    cluster layer). Parametrized by seed so fuzz campaigns can sweep
    fresh workloads (CI pins one)."""
    from cluster_helpers import join_node, make_cluster, req

    def http_ex(servers, rng):
        """Executor facade that routes each PQL via a random node."""
        class _E:
            def execute(self, index, pql):
                s = servers[int(rng.integers(0, len(servers)))]
                return req(
                    "POST",
                    f"http://localhost:{s.port}/index/{index}/query",
                    pql.encode(),
                )["results"]
        return _E()

    def check(servers, oracle):
        for s in servers:
            url = f"http://localhost:{s.port}/index/i/query"
            for row in ROWS:
                out = req("POST", url, f"Count(Row(f={row}))".encode())
                assert out["results"] == [len(oracle.sets[row])], (
                    s.config.name, row,
                )
            out = req("POST", url, b"Row(f=1)")
            assert out["results"][0]["columns"] == sorted(
                oracle.sets[1]
            ), s.config.name
            if oracle.values:
                out = req("POST", url, b'Sum(field="v")')
                assert out["results"][0] == {
                    "value": sum(oracle.values.values()),
                    "count": len(oracle.values),
                }, s.config.name
            for row in MUTEX_ROWS:
                out = req("POST", url, f"Count(Row(m={row}))".encode())
                assert out["results"] == [len(oracle.mutex_row(row))]

    rng = np.random.default_rng(seed)
    servers = make_cluster(tmp_path, 2, replica_n=2, prefix="cnode")
    try:
        base = f"http://localhost:{servers[0].port}"
        req("POST", f"{base}/index/i", {"options": {"trackExistence": True}})
        req("POST", f"{base}/index/i/field/f", {})
        req("POST", f"{base}/index/i/field/v",
            {"options": {"type": "int", "min": INT_MIN, "max": INT_MAX}})
        req("POST", f"{base}/index/i/field/m", {"options": {"type": "mutex"}})
        req("POST", f"{base}/index/i/field/b", {"options": {"type": "bool"}})
        req("POST", f"{base}/index/i/field/t",
            {"options": {"type": "time", "timeQuantum": "YMDH"}})

        oracle = Oracle()
        random_workload(rng, http_ex(servers, rng), "i", oracle, n_ops=80)
        check(servers, oracle)

        # a third node joins mid-workload; the async resize must finish
        # and the data must keep matching the oracle from ALL nodes
        late = join_node(tmp_path, servers[0], replica_n=2,
                         name="c2", prefix="cnode2")
        servers.append(late)
        assert late.api.cluster.wait_until_normal(30)
        random_workload(rng, http_ex(servers, rng), "i", oracle, n_ops=80)
        check(servers, oracle)

        # graceful leave: survivors must still answer for every shard
        leaver = servers.pop()
        leaver.api.cluster.leave()
        leaver.close()
        assert servers[0].api.cluster.wait_until_normal(30)
        random_workload(rng, http_ex(servers, rng), "i", oracle, n_ops=40)
        check(servers, oracle)
    finally:
        for s in servers:
            s.close()


@pytest.mark.parametrize("seed", [10, 11])
def test_local_and_mesh_executors_agree(tmp_path, seed):
    """The same random workload produces identical results from the
    single-device executor and the shard_map mesh executor."""
    rng = np.random.default_rng(seed)
    holder = make_env(tmp_path, "d")
    ex = Executor(holder)
    dx = DistExecutor(holder)
    oracle = Oracle()
    try:
        random_workload(rng, ex, "i", oracle, n_ops=150)
        queries = [random_expr(rng)[0] for _ in range(5)]
        queries += [f"Count({random_expr(rng)[0]})" for _ in range(5)]
        queries += ["All()", "TopN(f)", "Rows(f)", "GroupBy(Rows(f))",
                    'Sum(field="v")', 'Min(field="v")', 'Max(field="v")',
                    "Range(v > 100)", "Count(Range(v <= 0))",
                    "Row(m=1)", "Count(Row(m=2))", "Row(b=true)",
                    "Row(b=false)", "Rows(m)", "GroupBy(Rows(b))",
                    "Row(t=1)",
                    "Row(t=7, from='2019-01-01T00:00', to='2020-01-01T00:00')",
                    "Union(Row(m=0), Row(b=true), Row(f=1))"]
        for pql in queries:
            (a,) = ex.execute("i", pql)
            (b,) = dx.execute("i", pql)
            if hasattr(a, "columns"):
                assert a.columns().tolist() == b.columns().tolist(), pql
            else:
                assert a == b, pql
    finally:
        holder.close()
