"""Randomized full-stack property tests: a random write workload followed
by the whole read-query surface, checked against a pure-python oracle and
cross-checked between the local and mesh executors.

This is the end-to-end analog of the reference's oracle-checked randomized
container tests (roaring_test.go quick-check style — SURVEY.md §4): the
writes go through the real storage tree (fragments, op logs, caches), the
queries through the real compiled kernels, and nothing is mocked.
"""

import functools
import operator

import numpy as np
import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.result import ValCount
from pilosa_tpu.parallel.dist import DistExecutor
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage import FieldOptions, Holder

N_SHARDS = 3
COL_SPACE = N_SHARDS * SHARD_WIDTH
ROWS = [1, 2, 3, 7]
INT_MIN, INT_MAX = -50, 1000


class Oracle:
    """Pure-python model: field -> row -> set of columns; int field ->
    col -> value; the index existence set."""

    def __init__(self):
        self.sets: dict[int, set[int]] = {r: set() for r in ROWS}
        self.values: dict[int, int] = {}
        self.exists: set[int] = set()

    def set_bit(self, row, col):
        self.sets[row].add(col)
        self.exists.add(col)

    def clear_bit(self, row, col):
        self.sets[row].discard(col)

    def set_value(self, col, val):
        self.values[col] = val
        self.exists.add(col)


def random_workload(rng, ex, index, oracle, n_ops=120):
    """Random Set/Clear/value writes through PQL."""
    for _ in range(n_ops):
        col = int(rng.integers(0, COL_SPACE))
        op = rng.random()
        if op < 0.55:
            row = int(rng.choice(ROWS))
            ex.execute(index, f"Set({col}, f={row})")
            oracle.set_bit(row, col)
        elif op < 0.75:
            row = int(rng.choice(ROWS))
            ex.execute(index, f"Clear({col}, f={row})")
            oracle.clear_bit(row, col)
        else:
            val = int(rng.integers(INT_MIN, INT_MAX + 1))
            ex.execute(index, f"Set({col}, v={val})")
            oracle.set_value(col, val)


def random_expr(rng, depth=0):
    """Random bitmap expression tree -> (pql, eval(oracle) -> set)."""
    r = rng.random()
    if depth >= 2 or r < 0.35:
        row = int(rng.choice(ROWS))
        return f"Row(f={row})", lambda o: set(o.sets[row])
    op = rng.choice(["Union", "Intersect", "Difference", "Xor", "Not"])
    if op == "Not":
        pql, ev = random_expr(rng, depth + 1)
        return f"Not({pql})", lambda o: o.exists - ev(o)
    n = 2 if op in ("Difference", "Xor") else int(rng.integers(2, 4))
    subs = [random_expr(rng, depth + 1) for _ in range(n)]
    pql = f"{op}({', '.join(p for p, _ in subs)})"

    def ev(o, op=op, subs=subs):
        vals = [e(o) for _, e in subs]
        if op == "Union":
            return set().union(*vals)
        if op == "Intersect":
            return functools.reduce(operator.and_, vals)
        if op == "Difference":
            return vals[0] - vals[1]
        return vals[0] ^ vals[1]

    return pql, ev


def make_env(tmp_path, name):
    holder = Holder(str(tmp_path / name)).open()
    idx = holder.create_index("i", track_existence=True)
    idx.create_field("f")
    idx.create_field("v", FieldOptions(type="int", min=INT_MIN, max=INT_MAX))
    return holder


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_workload_vs_oracle(tmp_path, seed):
    rng = np.random.default_rng(seed)
    holder = make_env(tmp_path, "d")
    ex = Executor(holder)
    oracle = Oracle()
    try:
        for round_ in range(3):
            random_workload(rng, ex, "i", oracle, n_ops=60)

            # bitmap expressions + counts
            for _ in range(6):
                pql, ev = random_expr(rng)
                want = ev(oracle)
                (res,) = ex.execute("i", pql)
                assert set(res.columns().tolist()) == want, pql
                (n,) = ex.execute("i", f"Count({pql})")
                assert n == len(want), pql

            # existence
            (res,) = ex.execute("i", "All()")
            assert set(res.columns().tolist()) == oracle.exists

            # BSI: every compare op + aggregates against the value map
            vals = oracle.values
            for op_pql, pred in [
                (">", lambda v, k: v > k), ("<", lambda v, k: v < k),
                (">=", lambda v, k: v >= k), ("<=", lambda v, k: v <= k),
                ("==", lambda v, k: v == k), ("!=", lambda v, k: v != k),
            ]:
                k = int(rng.integers(INT_MIN, INT_MAX + 1))
                (res,) = ex.execute("i", f"Range(v {op_pql} {k})")
                want = {c for c, v in vals.items() if pred(v, k)}
                assert set(res.columns().tolist()) == want, (op_pql, k)
            if vals:
                (s,) = ex.execute("i", 'Sum(field="v")')
                assert s == ValCount(sum(vals.values()), len(vals))
                (mn,) = ex.execute("i", 'Min(field="v")')
                assert mn.value == min(vals.values())
                (mx,) = ex.execute("i", 'Max(field="v")')
                assert mx.value == max(vals.values())

            # TopN (cache is large enough to be exact) and Rows
            (pairs,) = ex.execute("i", "TopN(f)")
            want_pairs = sorted(
                ((r, len(c)) for r, c in oracle.sets.items() if c),
                key=lambda t: (-t[1], t[0]),
            )
            assert [(p.id, p.count) for p in pairs] == want_pairs
            (rows,) = ex.execute("i", "Rows(f)")
            assert rows == sorted(r for r, c in oracle.sets.items() if c)

            # GroupBy counts per row
            (groups,) = ex.execute("i", "GroupBy(Rows(f))")
            got = {g.group[0]["rowID"]: g.count for g in groups}
            assert got == {r: len(c) for r, c in oracle.sets.items() if c}

            # round-4 surface: TopN(threshold=) and GroupBy(having=)
            # against the same oracle, with a random floor
            thr = int(rng.integers(1, 40))
            (pairs,) = ex.execute("i", f"TopN(f, threshold={thr})")
            assert [(p.id, p.count) for p in pairs] == [
                (r, n) for r, n in want_pairs if n >= thr
            ]
            (groups,) = ex.execute(
                "i", f"GroupBy(Rows(f), having=Condition(count >= {thr}))"
            )
            got = {g.group[0]["rowID"]: g.count for g in groups}
            assert got == {r: len(c) for r, c in oracle.sets.items()
                           if len(c) >= thr}

            # pipelined submit() answers exactly as execute() (quiescent
            # holder: leaves captured at enqueue match)
            from pilosa_tpu.executor.result import result_to_json

            pqls = [f"Count({random_expr(rng)[0]})" for _ in range(6)]
            pqls += ["TopN(f)", "GroupBy(Rows(f))", 'Sum(field="v")']
            defs = [ex.submit("i", p)[0] for p in pqls]
            for p, d in zip(pqls, defs):
                want_r = result_to_json(ex.execute("i", p)[0])
                assert result_to_json(d.result()) == want_r, p
    finally:
        holder.close()


@pytest.mark.parametrize("seed", [10, 11])
def test_local_and_mesh_executors_agree(tmp_path, seed):
    """The same random workload produces identical results from the
    single-device executor and the shard_map mesh executor."""
    rng = np.random.default_rng(seed)
    holder = make_env(tmp_path, "d")
    ex = Executor(holder)
    dx = DistExecutor(holder)
    oracle = Oracle()
    try:
        random_workload(rng, ex, "i", oracle, n_ops=100)
        queries = [random_expr(rng)[0] for _ in range(5)]
        queries += [f"Count({random_expr(rng)[0]})" for _ in range(5)]
        queries += ["All()", "TopN(f)", "Rows(f)", "GroupBy(Rows(f))",
                    'Sum(field="v")', 'Min(field="v")', 'Max(field="v")',
                    "Range(v > 100)", "Count(Range(v <= 0))"]
        for pql in queries:
            (a,) = ex.execute("i", pql)
            (b,) = dx.execute("i", pql)
            if hasattr(a, "columns"):
                assert a.columns().tolist() == b.columns().tolist(), pql
            else:
                assert a == b, pql
    finally:
        holder.close()
