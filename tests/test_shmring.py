"""Unit suite for the multi-process serving tier's shared-memory ring
(pilosa_tpu/serving/shmring.py — ISSUE 11): framing round-trips,
every-offset torn-record fuzz (the PR-5 torn-tail shape applied to
shared memory), backpressure/full-ring behavior, and dead-reader slot
reclaim. Everything here is in-process — the subprocess end-to-end
contract lives in tests/test_mpserve.py."""

import os
import struct
import threading

import pytest

from pilosa_tpu.serving.shmring import (
    _HDR_SIZE,
    _SLOT_HDR,
    RingFull,
    ShmRing,
    decode_frame,
    encode_frame,
)

_UNIQ = iter(range(1, 1 << 30))


def _ring(slots=8, slot_bytes=256) -> ShmRing:
    name = f"psrv-test-{os.getpid():x}-{next(_UNIQ)}"
    return ShmRing.create(name, slots, slot_bytes)


@pytest.fixture
def ring():
    r = _ring()
    yield r
    r.close()
    r.unlink()


# ------------------------------------------------------------- framing


class TestFraming:
    def test_round_trip(self):
        header = {"op": "q", "ix": "i", "t": "tenant-1", "id": 7}
        body = b"Count(Row(f=1))"
        h, b = decode_frame(encode_frame(header, body))
        assert h == header
        assert b == body

    def test_empty_body(self):
        h, b = decode_frame(encode_frame({"st": 200}))
        assert h == {"st": 200}
        assert b == b""

    def test_binary_body_passes_untouched(self):
        body = bytes(range(256)) * 3
        _, b = decode_frame(encode_frame({}, body))
        assert b == body

    @pytest.mark.parametrize("record", [
        b"", b"\x01", b"\x00\x00\x00",                 # shorter than prefix
        struct.pack("<I", 999) + b"{}",                # hlen beyond record
        struct.pack("<I", 4) + b"nope",                # not JSON
        struct.pack("<I", 2) + b"[]",                  # JSON, not an object
    ])
    def test_malformed_raises_value_error(self, record):
        with pytest.raises(ValueError):
            decode_frame(record)


# ---------------------------------------------------------- ring basics


class TestRingBasics:
    def test_push_pop_round_trip(self, ring):
        recs = [f"record-{i}".encode() for i in range(5)]
        for rec in recs:
            assert ring.push(rec)
        assert [ring.pop() for _ in recs] == recs
        assert ring.pop() is None
        assert ring.metrics()["pushed"] == 5
        assert ring.metrics()["popped"] == 5

    def test_attach_sees_creator_records(self, ring):
        ring.push(b"cross-process bytes")
        peer = ShmRing.attach(ring.name)
        try:
            assert peer.slots == ring.slots
            assert peer.slot_bytes == ring.slot_bytes
            assert peer.pop() == b"cross-process bytes"
        finally:
            peer.close()

    def test_multi_slot_record_spans_and_round_trips(self):
        ring = _ring(slots=8, slot_bytes=256)
        try:
            big = os.urandom(256 * 3 + 57)  # 4 chunks
            assert ring.push(big)
            assert ring.depth() == 4
            assert ring.pop() == big
            assert ring.depth() == 0
            # wrap-around: repeat past the ring's end
            for _ in range(5):
                assert ring.push(big)
                assert ring.pop() == big
        finally:
            ring.close()
            ring.unlink()

    def test_record_beyond_capacity_raises(self):
        ring = _ring(slots=4, slot_bytes=256)
        try:
            with pytest.raises(RingFull):
                ring.push(b"x" * (4 * 256 + 1))
        finally:
            ring.close()
            ring.unlink()

    def test_create_validates_geometry(self):
        with pytest.raises(ValueError):
            ShmRing.create(f"psrv-test-{os.getpid():x}-g1", 1, 256)
        with pytest.raises(ValueError):
            ShmRing.create(f"psrv-test-{os.getpid():x}-g2", 8, 64)

    def test_drain_returns_batch(self, ring):
        for i in range(6):
            ring.push(f"r{i}".encode())
        assert ring.drain() == [f"r{i}".encode() for i in range(6)]
        assert ring.drain() == []

    def test_waiting_flag_handoff(self, ring):
        assert not ring.take_waiting()
        ring.set_waiting()
        assert ring.take_waiting()
        assert not ring.take_waiting()  # consumed


# --------------------------------------------------------- backpressure


class TestBackpressure:
    def test_full_ring_rejects_and_counts(self):
        ring = _ring(slots=4, slot_bytes=256)
        try:
            payload = b"y" * 200
            for _ in range(4):
                assert ring.push(payload)
            assert not ring.push(payload)  # full: shed, don't queue
            assert not ring.push(payload)
            assert ring.metrics()["full_rejects"] == 2
            # consuming one slot frees exactly one record's space
            assert ring.pop() == payload
            assert ring.push(payload)
        finally:
            ring.close()
            ring.unlink()

    def test_multi_chunk_needs_contiguous_free_slots(self):
        ring = _ring(slots=4, slot_bytes=256)
        try:
            assert ring.push(b"a" * 256)
            assert not ring.push(b"b" * (256 * 3 + 1))  # needs 4, has 3
            assert ring.metrics()["full_rejects"] == 1
            ring.pop()
            assert ring.push(b"b" * (256 * 3 + 1))
        finally:
            ring.close()
            ring.unlink()

    def test_spsc_threaded_ordering_under_backpressure(self):
        """A producer thread pushing through a tiny ring (retry on
        full) and a consumer popping: every record arrives, in order —
        the in-process locks plus the SPSC cursor protocol."""
        ring = _ring(slots=2, slot_bytes=256)
        try:
            n = 500
            got: list[bytes] = []

            def producer():
                for i in range(n):
                    rec = f"m{i}".encode()
                    while not ring.push(rec):
                        pass

            t = threading.Thread(target=producer)
            t.start()
            while len(got) < n:
                rec = ring.pop()
                if rec is not None:
                    got.append(rec)
            t.join(10)
            assert got == [f"m{i}".encode() for i in range(n)]
        finally:
            ring.close()
            ring.unlink()


# ------------------------------------------------------ torn-record fuzz


class TestTornRecords:
    """The PR-5 every-offset fuzz shape, applied to the ring: corrupt
    one byte at EVERY offset of a published record's slot (header and
    payload) and the consumer must surface either nothing (torn —
    counted and skipped) or, never, garbage; the following record is
    always still delivered."""

    def test_corruption_at_every_offset_is_skipped_never_decoded(self):
        payload = bytes(range(64))
        follow = b"follower-record"
        slot_span = _SLOT_HDR.size + len(payload)
        for off in range(slot_span):
            ring = _ring(slots=8, slot_bytes=256)
            try:
                assert ring.push(payload)
                assert ring.push(follow)
                # flip one byte of the first record's slot (slot 0)
                pos = _HDR_SIZE + off
                ring._buf[pos] ^= 0xFF
                first = ring.pop()
                # either detected-and-skipped (None) or — only when the
                # flip landed on a byte that round-trips (impossible for
                # seq/len/crc/payload, all covered by the checks) — the
                # original bytes; NEVER altered bytes
                assert first is None, f"offset {off} yielded {first!r}"
                assert ring.torn == 1, f"offset {off}"
                assert ring.pop() == follow, f"offset {off}"
            finally:
                ring.close()
                ring.unlink()

    def test_unpublished_record_is_invisible(self, ring):
        """A producer dying mid-write (head never advanced) leaves
        nothing: the consumer sees an empty ring, not a torn record."""
        ring.push(b"will-be-unpublished")
        # rewind head as if the crash happened before publication
        struct.pack_into("<Q", ring._buf, 16, 0)
        assert ring.pop() is None
        assert ring.torn == 0
        assert ring.depth() == 0

    def test_torn_multichunk_record_skips_its_whole_chain(self):
        """Corruption in chunk 0 of a multi-chunk record must consume
        the WHOLE chunk chain — the surviving continuation chunks
        (valid seq + crc) must never be reassembled into a headless
        record; the next pop yields the next real record."""
        ring = _ring(slots=8, slot_bytes=256)
        try:
            big = os.urandom(256 * 2 + 40)  # 3 chunks
            follow = b"next-record"
            ring.push(big)
            ring.push(follow)
            ring._buf[_HDR_SIZE + _SLOT_HDR.size] ^= 0xFF  # chunk 0 byte
            assert ring.pop() is None
            assert ring.torn == 1
            assert ring.pop() == follow
        finally:
            ring.close()
            ring.unlink()

    def test_promised_continuation_missing_is_torn(self):
        """head covering only the first chunk of a multi-chunk record
        (cannot happen with a live correct producer) is detected as
        torn, not an infinite wait."""
        ring = _ring(slots=8, slot_bytes=256)
        try:
            ring.push(b"z" * 300)  # 2 chunks
            struct.pack_into("<Q", ring._buf, 16, 1)  # head: 1 chunk only
            assert ring.pop() is None
            assert ring.torn == 1
        finally:
            ring.close()
            ring.unlink()


# ------------------------------------------------------------- reclaim


class TestReclaim:
    def test_dead_reader_slots_reclaimed_and_ring_reusable(self):
        ring = _ring(slots=8, slot_bytes=256)
        try:
            ring.push(b"one")
            ring.push(b"x" * 300)  # 2 chunks — counts as ONE record
            ring.push(b"three")
            assert ring.depth() == 4
            assert ring.reclaim() == 3  # records, not chunks
            assert ring.depth() == 0
            assert ring.pop() is None
            # immediately reusable after the reap
            assert ring.push(b"after")
            assert ring.pop() == b"after"
        finally:
            ring.close()
            ring.unlink()

    def test_reclaim_empty_ring_is_zero(self, ring):
        assert ring.reclaim() == 0
