"""Same-run Pallas-vs-XLA comparison for the fused intersect-count op.

VERDICT r3 #3 asked for the Pallas question to be settled with data
whenever the XLA kernel sits below ~0.8 of the HBM roofline. This
harness measures, in ONE process run on the real chip (the tunnel
drifts ±25% between runs — only same-run ratios mean anything):

  1. the XLA fused kernel (the bench.py ceiling op):
     per-row sum(popcount(a & (b ^ salt))) over uint32[R, W];
  2. a Pallas grid kernel for the same op at several VMEM block sizes
     (R-row operand blocks, grid over the word axis, accumulating
     per-row partial counts in the revisited output block).

Timing is INTERLEAVED: each trial runs one pipelined pass of every
variant back-to-back, so all variants sample the same seconds of tunnel
drift; best-of-TRIALS per variant. (The earlier sequential schedule
measured the same XLA kernel at 1.25e12 then 1.60e12 cols/s within one
process — larger than any XLA-vs-Pallas gap it was trying to resolve.)

History: the round-2 measurement (README "Kernel strategy") found
parity — Pallas 287-319 GB/s vs XLA 309-333 GB/s interleaved — and the
Pallas path was retired. Round 4's roofline fields put the XLA kernel
at 0.63-0.77 of the 819 GB/s v5e spec depending on run, keeping the
question open; re-run this harness when the op or toolchain changes.

Prints one JSON line per variant; correctness is asserted against the
XLA reference counts before any timing is reported.

Operands are generated ON DEVICE (jax.random.bits) rather than uploaded:
a 2 GiB host→device transfer through the degraded tunnel was observed
to stall past a 25-minute timeout (round 5), while generation costs two
device-side PRNG programs. Correctness gating is two-level: the XLA
kernel's counts are pinned against numpy at a small shape (1 MiB slice
readback), and every Pallas variant must match the XLA kernel's counts
at the full shape.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _stage(msg: str) -> None:
    """Progress marker on stderr so a tunnel stall is attributable."""
    print(f"[bench_pallas +{time.monotonic() - _T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.monotonic()

R = 8
N_COLS = 1 << 30
W = N_COLS // 32  # 2^25 words per row
ITERS = 64
TRIALS = 6
HBM_PEAK = 819e9


def pallas_intersect_count(block_w: int, rows: int = R, words: int = W,
                           interpret: bool = False):
    """Pallas grid kernel for per-row sum(popcount(a & (b ^ salt))).
    ``interpret=True`` runs the kernel logic on any backend (the CI test
    pins it against a numpy oracle without TPU hardware)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(salt_ref, a_ref, b_ref, out_ref):
        w = pl.program_id(0)
        s = salt_ref[0]
        x = a_ref[:] & (b_ref[:] ^ s)
        c = jnp.sum(lax.population_count(x).astype(jnp.int32), axis=1,
                    keepdims=True)

        @pl.when(w == 0)
        def _():
            out_ref[:] = c

        @pl.when(w != 0)
        def _():
            out_ref[:] = out_ref[:] + c

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(words // block_w,),
        in_specs=[
            pl.BlockSpec((rows, block_w), lambda w, s: (0, w)),
            pl.BlockSpec((rows, block_w), lambda w, s: (0, w)),
        ],
        out_specs=pl.BlockSpec((rows, 1), lambda w, s: (0, 0)),
    )
    return jax.jit(
        lambda a, b, salt: pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, 1), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(salt, a, b)
    )


class Variant:
    """One kernel variant: compile + correctness-gate up front, then the
    harness interleaves timing passes round-robin across variants so
    every variant samples the SAME seconds of tunnel drift — the r5
    sequential run measured the XLA kernel at 1.25e12 then 1.60e12
    within one process, larger than any XLA-vs-Pallas gap."""

    def __init__(self, fn, name, wrap):
        self.fn, self.name, self.wrap = fn, name, wrap
        self.salt = 0
        self.best = float("inf")
        self.ok = False

    def compile_and_gate(self, a, b, expect=None):
        """Compile + reference counts (BEFORE any timing is reported — a
        wrong variant prints an error line and no numbers). Errors never
        abort the harness: the remaining variants still compare."""
        try:
            ref = np.asarray(self.fn(a, b, self.wrap(self.salt)))
        except Exception as e:  # noqa: BLE001 — report and keep comparing
            print(json.dumps({
                "variant": self.name, "error": f"{type(e).__name__}: {e}"
            }), flush=True)
            return None
        if expect is not None and not np.array_equal(
            ref.ravel().astype(np.int64), expect.astype(np.int64)
        ):
            print(json.dumps({
                "variant": self.name,
                "error":
                    f"wrong counts: {ref.ravel().tolist()} != {expect.tolist()}",
            }), flush=True)
            return None
        self.salt += 1
        self.ok = True
        return ref.ravel()

    def timed_pass(self, a, b):
        """One pipelined pass of ITERS calls; keeps the best per-call dt.
        cols_per_sec counts all R row-queries per call, the same unit as
        bench.py's kernel_cols_per_sec (K_ROWS · n_cols / dt)."""
        t0 = time.perf_counter()
        out = None
        for _ in range(ITERS):
            out = self.fn(a, b, self.wrap(self.salt))
            self.salt += 1
        np.asarray(out)  # stream-ordered: last done => all done
        self.best = min(self.best, (time.perf_counter() - t0) / ITERS)

    def report(self) -> None:
        rate = R * N_COLS / self.best
        print(json.dumps({
            "variant": self.name, "cols_per_sec": round(rate, 1),
            "hbm_bytes_per_sec": round(rate / 4, 1),
            "frac_hbm_peak": round((rate / 4) / HBM_PEAK, 3),
            "iters": ITERS, "trials": TRIALS, "schedule": "interleaved",
        }), flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax, random

    _stage("importing jax / first device op")
    jnp.add(1, 1).block_until_ready()
    _stage("generating operands on device")
    bits = jax.jit(lambda k: random.bits(k, (R, W), jnp.uint32))
    a = bits(random.key(1))
    b = bits(random.key(2))
    jax.block_until_ready((a, b))

    @jax.jit
    def xla_kernel(a, b, salt):
        return jnp.sum(
            lax.population_count(a & (b ^ salt)).astype(jnp.uint32), axis=1
        )

    # small-shape numpy gate: the same fused op on a 1 MiB slice readback
    # pins the XLA kernel against the host before the full-shape ratios
    # (full operands never leave the device).
    _stage("small-shape numpy correctness gate")
    w_small = 1 << 15
    a_s = np.asarray(a[:, :w_small])
    b_s = np.asarray(b[:, :w_small])
    got = np.asarray(xla_kernel(a[:, :w_small], b[:, :w_small],
                                jnp.uint32(5)))
    want = np.bitwise_count(a_s & (b_s ^ np.uint32(5))).sum(
        axis=1, dtype=np.uint64
    )
    if not np.array_equal(got.astype(np.uint64), want):
        print(json.dumps({"variant": "xla_small_gate",
                          "error": f"{got.tolist()} != {want.tolist()}"}),
              flush=True)
        return

    scalar = lambda s: jnp.uint32(s)  # noqa: E731
    vec1 = lambda s: np.full(1, s, np.uint32)  # noqa: E731

    variants = [Variant(xla_kernel, "xla", scalar)]
    for bw in (1 << 15, 1 << 16, 1 << 17, 1 << 18):
        variants.append(
            Variant(pallas_intersect_count(bw), f"pallas_bw{bw}", vec1)
        )

    _stage("compiling + gating variants")
    ref = variants[0].compile_and_gate(a, b)
    # ref=None (xla failed to compile) degrades the Pallas gates to
    # ungated rather than aborting: a broken reference variant must not
    # cost the run its remaining data points (errors never abort).
    for v in variants[1:]:
        v.compile_and_gate(a, b, expect=ref)
    live = [v for v in variants if v.ok]
    if not live:
        return

    # try/finally: a mid-run relay death (it happened twice this round)
    # must not lose the best-of-N-so-far data already held for every
    # variant — report whatever has at least one completed pass.
    try:
        for t in range(TRIALS):
            _stage(f"interleaved trial {t + 1}/{TRIALS} "
                   f"({', '.join(v.name for v in live)})")
            for v in live:
                v.timed_pass(a, b)
    finally:
        for v in live:
            if v.best < float("inf"):
                v.report()


if __name__ == "__main__":
    main()
